"""Fused single-dispatch ingest (tier-1 smoke, CPU, tiny arena).

The per-conversation ingest sequence — node scatter, dedup merge touch,
two-mode link scan, gated edge insert — must run as ONE device program
(``state.ingest_fused``): these tests count the actual jit entry points
during an end-to-end ``end_conversation`` and pin exact semantic parity
with the classic four-dispatch path, so donation/ownership regressions in
the fused pipeline are caught without the full bench.
"""

import json
import tempfile

import numpy as np
import pytest

from lazzaro_tpu.config import MemoryConfig
from lazzaro_tpu.core import state as S
from lazzaro_tpu.core.index import MemoryIndex
from lazzaro_tpu.core.memory_system import MemorySystem
from lazzaro_tpu.utils.batching import IngestCoalescer

D = 24
_DIRS = np.random.default_rng(3).standard_normal((10, D))
_DIRS /= np.linalg.norm(_DIRS, axis=1, keepdims=True)


class ClusteredEmb:
    """Facts in the same group land ~0.8 cosine apart: above the 0.5 link
    gate (real gated links), below the 0.95 dedup gate (distinct nodes)."""

    dim = D

    def _v(self, t):
        try:
            idx = int(t.split()[1])
        except (IndexError, ValueError):
            idx = abs(hash(t)) % 100
        rng = np.random.default_rng(500 + idx)
        v = 0.85 * _DIRS[idx % 10] + 0.55 * rng.standard_normal(D)
        return (v / np.linalg.norm(v)).tolist()

    def embed(self, t):
        return self._v(t)

    def batch_embed(self, ts):
        return [self._v(t) for t in ts]


class QueueLLM:
    def __init__(self, per=20):
        self.c = 0
        self.per = per

    def completion(self, messages, response_format=None):
        base = self.c * self.per
        self.c += 1
        return json.dumps({"memories": [
            {"content": f"fact {base + i} body", "type": "semantic",
             "salience": 0.6,
             "topic": ["work", "personal", "learning"][(base + i) % 3]}
            for i in range(self.per)]})

    def completion_stream(self, messages, response_format=None):
        yield self.completion(messages, response_format)


def _system(tmp, fused=True, per=20):
    return MemorySystem(
        enable_async=False, db_dir=tmp, verbose=False, load_from_disk=False,
        llm_provider=QueueLLM(per), embedding_provider=ClusteredEmb(),
        auto_prune=False, max_buffer_size=10_000,
        config=MemoryConfig(journal=False, auto_consolidate=False,
                            ingest_fused=fused, decay_rate=0.0))


_COUNTED = ("ingest_fused", "ingest_fused_copy", "ingest_dedup_fused",
            "ingest_dedup_fused_copy", "arena_add",
            "arena_add_copy", "arena_merge_touch", "arena_merge_touch_copy",
            "edges_add", "edges_add_copy", "arena_link_candidates_multi",
            "arena_search")


def _count_dispatches(monkeypatch):
    calls = {name: 0 for name in _COUNTED}
    for name in _COUNTED:
        orig = getattr(S, name)

        def wrapped(*a, __orig=orig, __name=name, **kw):
            calls[__name] += 1
            return __orig(*a, **kw)

        monkeypatch.setattr(S, name, wrapped)
    return calls


def test_one_fused_dispatch_per_conversation(monkeypatch):
    """The jit-call counter: a consolidated conversation costs exactly ONE
    ingest-path dispatch (the dedup-fused program — the dedup probe rides
    inside it, so no separate ``arena_search`` dispatch either), zero
    unfused mutation calls."""
    with tempfile.TemporaryDirectory() as tmp:
        ms = _system(tmp, fused=True)
        ms.start_conversation()
        ms.add_to_short_term("conv 0", "episodic", 0.7)
        calls = _count_dispatches(monkeypatch)
        ms.end_conversation()
        assert (calls["ingest_dedup_fused"]
                + calls["ingest_dedup_fused_copy"]) == 1
        # the single-writer hot path donated (no reader held the state)
        assert calls["ingest_dedup_fused"] == 1
        for name in ("ingest_fused", "ingest_fused_copy", "arena_add",
                     "arena_add_copy", "arena_merge_touch",
                     "arena_merge_touch_copy", "edges_add", "edges_add_copy",
                     "arena_link_candidates_multi", "arena_search"):
            assert calls[name] == 0, (name, calls)
        assert ms.buffer.size()[0] == 20
        ms.close()


def test_one_dispatch_with_device_dedup_duplicates(monkeypatch):
    """Same counter with REAL duplicates in the batch: the device merges
    them inside the one dispatch (no probe dispatch, no separate merge
    touch), and the graph matches the classic host-probe pipeline."""
    class DupLLM(QueueLLM):
        def completion(self, messages, response_format=None):
            out = json.loads(super().completion(messages, response_format))
            # repeat the first two facts verbatim: exact-cosine duplicates
            out["memories"] += [dict(out["memories"][0]),
                                dict(out["memories"][1])]
            return json.dumps(out)

    def build(dedup_fused):
        tmp = tempfile.mkdtemp()
        ms = _system(tmp, fused=True)
        ms.config.ingest_dedup_fused = dedup_fused
        ms.llm = DupLLM(8)
        ms.start_conversation()
        ms.add_to_short_term("conv 0", "episodic", 0.7)
        return ms

    ms = build(True)
    calls = _count_dispatches(monkeypatch)
    ms.end_conversation()
    assert calls["ingest_dedup_fused"] == 1
    assert calls["arena_search"] == 0
    assert ms.buffer.size()[0] == 8          # 2 duplicates merged, not added
    classic = build(False)
    classic.end_conversation()
    try:
        assert set(ms.buffer.nodes) == set(classic.buffer.nodes)
        na = {n: (round(ms.buffer.nodes[n].salience, 5),
                  ms.buffer.nodes[n].access_count)
              for n in ms.buffer.nodes}
        nb = {n: (round(classic.buffer.nodes[n].salience, 5),
                  classic.buffer.nodes[n].access_count)
              for n in classic.buffer.nodes}
        assert na == nb
        assert set(ms.index.edge_slots) == set(classic.index.edge_slots)
    finally:
        ms.close()
        classic.close()


def test_fused_matches_unfused_exactly():
    """Node set, host edge set (keys AND weights), device edge arena, and
    retrieval results must be identical across the two pipelines."""
    def build(fused):
        tmp = tempfile.mkdtemp()
        ms = _system(tmp, fused=fused)
        for c in range(3):
            ms.start_conversation()
            ms.add_to_short_term(f"conv {c}", "episodic", 0.7)
            ms.end_conversation()
        return ms

    a, b = build(True), build(False)
    try:
        assert a.buffer.size() == b.buffer.size()
        assert set(a.buffer.nodes) == set(b.buffer.nodes)

        def host_edges(ms):
            return {(e.source, e.target): round(e.weight, 5)
                    for s in ms.shards.values() for e in s.edges.values()}

        assert host_edges(a) == host_edges(b)
        assert set(a.index.edge_slots) == set(b.index.edge_slots)
        wa, wb = a.index.edge_weights(), b.index.edge_weights()
        for key in wa:
            assert wa[key][0] == pytest.approx(wb[key][0], abs=1e-5), key
            assert wa[key][1] == wb[key][1], key
        assert a.metrics["edges_linked"] == b.metrics["edges_linked"]
        for q in ("fact 7 body", "fact 31 body"):
            ra = [n.id for n in a.search_memories(q)]
            rb = [n.id for n in b.search_memories(q)]
            assert ra == rb
    finally:
        a.close()
        b.close()


def test_ingest_batch_candidates_match_link_candidates_multi():
    """The fused kernel's link output is the same scan the classic path
    runs after its add — byte-identical candidates either way."""
    rng = np.random.default_rng(11)
    seed_emb = rng.standard_normal((20, D)).astype(np.float32)
    new_emb = rng.standard_normal((4, D)).astype(np.float32)

    def seed_index():
        idx = MemoryIndex(dim=D, capacity=255)
        idx.add([f"m{i}" for i in range(20)], seed_emb, [0.5] * 20,
                [0.0] * 20, ["semantic"] * 20, ["default"] * 20, "u")
        return idx
    idx1, idx2 = seed_index(), seed_index()
    new_ids = [f"n{i}" for i in range(4)]
    common = dict(saliences=[0.5] * 4, timestamps=[0.0] * 4,
                  types=["semantic"] * 4, shard_keys=["default"] * 4)

    _rows, cands, _created = idx1.ingest_batch(
        new_ids, new_emb, tenant="u", link_k=3, **common)

    idx2.add(new_ids, new_emb, common["saliences"], common["timestamps"],
             common["types"], common["shard_keys"], "u")
    classic = idx2.link_candidates_multi(new_ids, "u", k=3, shard_modes=(1, 0))

    for mode in (1, 0):
        assert set(cands[mode]) == set(classic[mode])
        for nid in cands[mode]:
            got = [(c, round(s, 5)) for c, s in cands[mode][nid]]
            want = [(c, round(s, 5)) for c, s in classic[mode][nid]]
            assert got == want, (mode, nid)


def test_ingest_batch_reclaims_rejected_slots():
    """Slots pre-allocated for links the gate rejects go back to the free
    list; the live edge arena and the slot map stay consistent."""
    idx = MemoryIndex(dim=D, capacity=255, edge_capacity=1023)
    emb = np.eye(D, dtype=np.float32)[:8]     # orthogonal: nothing links
    free_before = len(idx._free_edge_slots)
    _rows, _cands, created = idx.ingest_batch(
        [f"o{i}" for i in range(8)], emb, [0.5] * 8, [0.0] * 8,
        ["semantic"] * 8, ["default"] * 8, "u",
        chain_pairs=[(f"o{i}", f"o{i+1}") for i in range(7)])
    assert created == {1: [], 0: []}          # gate rejected every link
    # only the 7 chain slots stay allocated
    assert len(idx._free_edge_slots) == free_before - 7
    assert len(idx.edge_slots) == 7
    # the edge arena agrees: exactly 7 alive edges
    assert int(np.asarray(idx.edge_state.alive).sum()) == 7


def test_coalescer_merges_and_splits():
    c = IngestCoalescer(max_facts=10)
    c.add_conversation([{"content": f"a{i}"} for i in range(4)])
    c.add_conversation([{"content": f"b{i}"} for i in range(4)])
    assert len(c) == 8 and c.pending_conversations == 2
    batches = c.drain()
    assert len(batches) == 1
    facts, n_convs = batches[0]
    assert len(facts) == 8 and n_convs == 2   # cross-conversation mega-batch
    assert len(c) == 0

    # conversations that don't fit together stay whole but separate
    c.add_conversation([{"content": f"a{i}"} for i in range(7)])
    c.add_conversation([{"content": f"b{i}"} for i in range(7)])
    batches = c.drain()
    assert [(len(f), n) for f, n in batches] == [(7, 1), (7, 1)]

    # an oversized single conversation splits, nothing dropped
    c.add_conversation([{"content": f"x{i}"} for i in range(23)])
    batches = c.drain()
    assert [len(f) for f, _ in batches] == [10, 10, 3]
    assert sum(n for _, n in batches) >= 1


def test_link_pool_hint_overflow_retry_exact_parity():
    """ISSUE 4 satellite (ROADMAP ceiling #2): with a tiny
    ``link_accept_hint`` the edge-slot pool under-provisions on purpose; a
    batch whose accepted links overflow it must (a) raise the in-kernel
    overflow flag / bump ``link_pool_overflows``, (b) re-insert exactly
    the overflowed edges host-side, ending bit-identical (keys, weights,
    created lists) to a worst-case-pool twin, and (c) never leak slots."""
    rng = np.random.default_rng(3)
    base = rng.standard_normal((1, 16)).astype(np.float32)

    def build():
        idx = MemoryIndex(dim=16, capacity=255, edge_capacity=512)
        seed_emb = (np.tile(base, (8, 1))
                    + 0.05 * rng.standard_normal((8, 16)).astype(np.float32))
        idx.add([f"s{i}" for i in range(8)], seed_emb, [0.5] * 8, [0.0] * 8,
                ["semantic"] * 8, ["default"] * 8, "u0")
        return idx

    rng = np.random.default_rng(3)          # same stream for both twins
    a = build()
    rng = np.random.default_rng(3)
    b = build()
    rng = np.random.default_rng(4)
    new_emb = (np.tile(base, (4, 1))
               + 0.05 * rng.standard_normal((4, 16)).astype(np.float32))
    args = ([f"n{i}" for i in range(4)], new_emb, [0.5] * 4, [0.0] * 4,
            ["semantic"] * 4, ["default"] * 4, "u0")
    kw = dict(link_k=3, link_gate=0.5, now=123.0)

    free_a = len(a._free_edge_slots)
    _, _, created_a = a.ingest_batch(*args, link_accept_hint=0.05, **kw)
    _, _, created_b = b.ingest_batch(*args, **kw)   # worst-case pool
    assert a.link_pool_overflows == 1
    assert b.link_pool_overflows == 0
    for sm in (1, 0):
        assert sorted(created_a[sm]) == sorted(created_b[sm])
    assert set(a.edge_slots) == set(b.edge_slots)
    wa, wb = a.edge_weights(), b.edge_weights()
    for key in wa:
        assert abs(wa[key][0] - wb[key][0]) < 1e-5, (key, wa[key], wb[key])
    # no slot leaked: free + registered == free_before (every edge holds 1)
    assert len(a._free_edge_slots) + len(a.edge_slots) == free_a


def test_link_pool_hint_no_overflow_shrinks_allocation():
    """A hint that still covers the acceptance rate must shrink the
    transient pool draw (the free list never dips to the worst case) and
    skip the retry entirely."""
    idx = MemoryIndex(dim=D, capacity=255, edge_capacity=1023)
    emb = np.eye(D, dtype=np.float32)[:8]     # orthogonal: nothing links
    _, _, created = idx.ingest_batch(
        [f"o{i}" for i in range(8)], emb, [0.5] * 8, [0.0] * 8,
        ["semantic"] * 8, ["default"] * 8, "u", link_k=3,
        link_accept_hint=0.25)
    assert created == {1: [], 0: []}
    assert idx.link_pool_overflows == 0
    # worst case would draw 2*8*3 = 48 pool slots; hint 0.25 draws 12
    assert idx._link_pool_size(48, 0.25) == 12
    assert idx._link_pool_size(48, 1.0) == 48
    assert idx._link_pool_size(48, 0.0) == 1   # floor: overflow path owns it


def test_dedup_fused_pool_hint_overflow_retry():
    """The dedup-fused mega-batch path honors the hint too: overflowed
    accepted links come back through ``commit_ingest_dedup``'s host retry
    with identical weights."""
    rng = np.random.default_rng(11)
    base = rng.standard_normal((1, 16)).astype(np.float32)

    def run(hint):
        idx = MemoryIndex(dim=16, capacity=255, edge_capacity=512)
        seed_emb = (np.tile(base, (6, 1))
                    + 0.05 * rng.standard_normal((6, 16)).astype(np.float32))
        idx.add([f"s{i}" for i in range(6)], seed_emb, [0.5] * 6, [0.0] * 6,
                ["semantic"] * 6, ["default"] * 6, "u0")
        new_emb = (np.tile(base, (3, 1))
                   + 0.05 * rng.standard_normal((3, 16)).astype(np.float32))
        pending = idx.ingest_batch_dedup(
            new_emb, [0.5] * 3, [0.0] * 3, ["semantic"] * 3,
            ["default"] * 3, "u0", dedup_gate=2.0, link_k=3,
            link_gate=0.5, now=99.0, link_accept_hint=hint)
        ids = [f"q{i}" for i in range(3)]
        _, created, _, _ = idx.commit_ingest_dedup(pending, ids)
        return idx, created

    rng = np.random.default_rng(11)
    a, created_a = run(0.05)
    rng = np.random.default_rng(11)
    b, created_b = run(1.0)
    assert a.link_pool_overflows == 1 and b.link_pool_overflows == 0
    for sm in (1, 0):
        assert sorted(created_a[sm]) == sorted(created_b[sm])
    assert set(a.edge_slots) == set(b.edge_slots)
    wa, wb = a.edge_weights(), b.edge_weights()
    for key in wa:
        assert abs(wa[key][0] - wb[key][0]) < 1e-5, (key, wa[key], wb[key])
