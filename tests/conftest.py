"""Test bootstrap: force an 8-device virtual CPU mesh BEFORE jax initializes.

The environment pins JAX_PLATFORMS=axon (one real TPU chip through a tunnel);
unit tests run on a deterministic 8-way CPU topology instead — the TPU analog
of the reference's "two instances on one LanceDB dir" cross-process tests
(SURVEY §4(e)).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
# Strip the accelerator-plugin vars: tests must NEVER touch the TPU tunnel
# (a leaked handle is what voided round 3), and with them absent the
# backend_probe env gate recognizes this as genuinely CPU-forced, so
# entry()/dryrun tests skip the (90 s on a wedged tunnel) subprocess probe.
for _var in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE"):
    os.environ.pop(_var, None)

# The axon sitecustomize pins the TPU backend via env at interpreter start;
# config.update after import is the reliable override in this image.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def tmp_db(tmp_path):
    return str(tmp_path / "db")
