"""Serving telemetry (ISSUE 6; tier-1 smoke, CPU, tiny arenas).

The observability layer must be free-riding by construction: host spans are
perf_counter bookkeeping around dispatches that already happen, and the
device-side counters are an int32 tail on the packed readback that already
exists. These tests pin the three claims that make it trustworthy:

- span accounting composes with coalescing — N requests flushed as ONE
  mega-batch yield N queue-wait samples and exactly 1 dispatch sample;
- the device counters decoded from the readback tail match host-computed
  truth on gate-hit / gate-miss / multi-tenant fixtures;
- telemetry adds ZERO device dispatches (the jit counter still reads 1 per
  chat turn, and cached turns stay zero-RTT) while visibly recording;

plus the exposure surfaces: the dashboard's Prometheus ``/metrics`` and
JSON ``/api/metrics`` must agree with ``MemorySystem.metrics_summary()``,
and fused-path counters must survive a checkpoint round trip.
"""

import json
import tempfile
import threading
import urllib.request

import numpy as np
import pytest

from lazzaro_tpu.core.index import MemoryIndex
from lazzaro_tpu.serve import QueryScheduler, RetrievalRequest
from lazzaro_tpu.utils.telemetry import Telemetry, split_key, timed
from tests.test_fused_retrieval import (_count_dispatches, _ingest,
                                        _system)

D = 16


# ------------------------------------------------------------ registry unit
def test_registry_labels_snapshot_prometheus():
    tel = Telemetry()
    tel.bump("serve.dispatches", labels={"mode": "exact"})
    tel.bump("serve.dispatches", 2, labels={"mode": "quant"})
    tel.record("serve.queue_wait_ms", 1.5, labels={"tenant": "a"})
    tel.record("serve.queue_wait_ms", 2.5, labels={"tenant": "a"})
    tel.gauge("serve.batch_occupancy", 0.75)

    assert tel.counter_total("serve.dispatches") == 3
    assert tel.timer_count("serve.queue_wait_ms") == 2
    assert sorted(tel.timer_values("serve.queue_wait_ms")) == [1.5, 2.5]

    snap = tel.snapshot()
    key = 'serve.queue_wait_ms{tenant="a"}'
    assert snap["timers"][key]["count"] == 2
    assert snap["timers"][key]["max_ms"] == 2.5
    assert snap["counters"]['serve.dispatches{mode="quant"}'] == 2
    assert snap["gauges"]["serve.batch_occupancy"] == 0.75
    json.dumps(snap)                       # the bench-artifact contract

    text = tel.prometheus()
    assert '# TYPE lazzaro_serve_dispatches_total counter' in text
    assert 'lazzaro_serve_dispatches_total{mode="exact"} 1' in text
    assert 'lazzaro_serve_dispatches_total{mode="quant"} 2' in text
    assert 'lazzaro_serve_queue_wait_ms_count{tenant="a"} 2' in text
    assert 'lazzaro_serve_batch_occupancy 0.75' in text

    name, label = split_key(key)
    assert (name, label) == ("serve.queue_wait_ms", '{tenant="a"}')


def test_label_cardinality_clamp():
    """A tenant explosion folds into one '~other' series instead of
    growing the registry without bound."""
    from lazzaro_tpu.utils import telemetry as T
    tel = Telemetry()
    for i in range(T.MAX_LABEL_SETS + 50):
        tel.bump("serve.requests", labels={"tenant": f"u{i}"})
    series = [k for k in tel.counters if k.startswith("serve.requests")]
    assert len(series) == T.MAX_LABEL_SETS + 1
    assert tel.counters['serve.requests{tenant="~other"}'] == 50
    assert tel.counter_total("serve.requests") == T.MAX_LABEL_SETS + 50


def test_disabled_registry_is_a_noop():
    tel = Telemetry(enabled=False)
    tel.bump("c")
    tel.record("t", 1.0)
    tel.gauge("g", 2.0)
    assert tel.snapshot() == {"timers": {}, "counters": {}, "gauges": {}}


def test_timed_routes_through_logging(capsys, caplog):
    """Satellite: ``timed()`` without a sink logs instead of printing, so
    library users silence it with standard logging config."""
    import logging
    with caplog.at_level(logging.INFO, logger="lazzaro_tpu.telemetry"):
        with timed("unit-test-label"):
            pass
    assert capsys.readouterr().out == ""
    assert any("unit-test-label" in r.getMessage() for r in caplog.records)


# ----------------------------------------------------- fixtures (tiny arena)
def _index(tel=None, **kw):
    idx = MemoryIndex(dim=D, capacity=64, edge_capacity=255,
                      telemetry=tel if tel is not None else Telemetry(),
                      **kw)
    return idx


def _basis(i):
    v = np.zeros(D, np.float32)
    v[i] = 1.0
    return v


def _fill_two_tenants(idx):
    """Tenant 'a': rows a0..a7 on basis vectors + one super row on e0;
    tenant 'b': rows b0..b7 + one super row on e15 (orthogonal to every
    test query, so its gate can never fire). Edges a0—a1 and a0—a2."""
    for t, base, sup_axis in (("a", 0, 0), ("b", 8, 15)):
        emb = np.stack([_basis((base + i) % D) for i in range(8)])
        idx.add([f"{t}{i}" for i in range(8)], emb, [0.5] * 8, [0.0] * 8,
                ["semantic"] * 8, ["default"] * 8, t)
        idx.add([f"s{t}"], _basis(sup_axis)[None, :], [0.9], [0.0],
                ["semantic"], ["default"], t, is_super=[True])
    idx.add_edges([("a0", "a1", 0.7), ("a0", "a2", 0.7)], "a")
    return idx


_KW = dict(cap_take=2, max_nbr=4, super_gate=0.4, acc_boost=0.05,
           nbr_boost=0.02)


# ------------------------------------------- scheduler span accounting
def test_coalesced_batch_yields_n_queue_waits_one_dispatch():
    """The ISSUE 6 accounting contract: N requests coalesced into ONE
    mega-batch must yield N queue-wait samples (per-tenant labelled) and
    exactly 1 dispatch sample / 1 dispatch counter bump."""
    tel = Telemetry()
    idx = _fill_two_tenants(_index(tel))
    release = threading.Event()
    in_first = threading.Event()
    batches = []

    def executor(reqs):
        batches.append(len(reqs))
        if len(batches) == 1:
            in_first.set()
            release.wait(timeout=10)
        return idx.search_fused_requests(reqs, **_KW)

    s = QueryScheduler(executor, max_batch=64, max_wait_us=500,
                       telemetry=tel)
    try:
        first = s.submit(RetrievalRequest(query=_basis(0), tenant="a"))
        assert in_first.wait(timeout=10)   # worker is now blocked mid-flush
        rest = s.submit_many(
            [RetrievalRequest(query=_basis(i % 8), tenant="a")
             for i in range(5)]
            + [RetrievalRequest(query=_basis(8 + i % 8), tenant="b")
               for i in range(5)])
        release.set()
        first.result(timeout=10)
        for f in rest:
            f.result(timeout=10)
    finally:
        s.close()

    assert batches == [1, 10]              # the 10 coalesced into ONE flush
    # 11 requests total → 11 queue-wait samples, split by tenant label
    assert tel.timer_count("serve.queue_wait_ms") == 11
    snap = tel.snapshot()
    assert snap["timers"]['serve.queue_wait_ms{tenant="a"}']["count"] == 6
    assert snap["timers"]['serve.queue_wait_ms{tenant="b"}']["count"] == 5
    # 2 flushes → 2 dispatch samples / bumps (1 for the coalesced batch)
    assert tel.counter_total("serve.dispatches") == 2
    assert tel.timer_count("serve.dispatch_ms") == 2
    assert tel.counter_total("serve.batches") == 2
    assert sorted(tel.timer_values("serve.batch_requests")) == [1, 10]
    assert tel.counter_total("serve.requests") == 11
    # pad-inflation accounting: 11 live requests, pow2-padded slots
    assert tel.counter_total("serve.live_requests") == 11
    assert tel.counter_total("serve.padded_slots") == 1 + 16


# ------------------------------------------------- device-counter parity
@pytest.mark.parametrize("mode", ["exact", "quant", "ivf"])
def test_device_counters_match_host_truth(mode):
    """Gate hit / gate miss / boost-row counts decoded from the readback
    tail must equal host-computed truth on a multi-tenant fixture, on
    every single-chip fused serving path."""
    tel = Telemetry()
    idx = _fill_two_tenants(_index(
        tel, int8_serving=(mode == "quant"),
        ivf_nprobe=4 if mode == "ivf" else 0))
    if mode == "ivf":
        idx._IVF_MIN_ROWS = 1
        assert idx.ivf_maintenance()
    qa = 0.8 * _basis(0) + 0.6 * _basis(1)   # top-2 = a0, a1; gate sa=0.8
    reqs = [
        # gate HIT for tenant a (sa is e0): fast path, boosts suppressed
        RetrievalRequest(query=qa, tenant="a", k=4, gate_enabled=True,
                         boost=True),
        # gate MISS for tenant b (sb is e15, orthogonal): boosts applied
        RetrievalRequest(query=_basis(8), tenant="b", k=4,
                         gate_enabled=True, boost=True),
        # no gate, boosts applied: acc = top-2 {a0, a1}; a0's neighbors
        # {a1, a2} minus the retrieved set → ONE neighbor boost row (a2)
        RetrievalRequest(query=qa, tenant="a", k=4, boost=True),
        # pure read: contributes nothing to any boost counter
        RetrievalRequest(query=_basis(9), tenant="b", k=4),
    ]
    res = idx.search_fused_requests(reqs, **_KW)

    assert res[0].fast and not res[1].fast and not res[2].fast
    assert tel.counter_total("device.gate_hit") == 1
    assert tel.counter_total("device.gate_miss") == 1
    # host truth for access-boost rows: every valid boosted non-fast query
    # scatters min(cap_take, live) rows — queries 1 and 2, 2 rows each
    assert tel.counter_total("device.boost_rows") == 4
    assert tel.counter_total("device.nbr_boost_rows") == 1
    # 8 live rows per tenant ≥ k=4 → no shortfall anywhere
    assert tel.counter_total("device.topk_shortfall") == 0
    assert tel.counter_total("device.dedup_hits") == 0
    assert tel.counter_total(f"serve.dispatches") == 1
    snap = tel.snapshot()
    assert snap["counters"][f'serve.dispatches{{mode="{mode}"}}'] == 1
    assert tel.timer_count("serve.dispatch_ms") == 1
    assert tel.timer_count("serve.decode_ms") == 1


def test_topk_shortfall_counts_against_requested_k():
    """A request asking for more rows than its tenant owns reports the gap
    through the device counter — against ITS k, not the padded bucket."""
    tel = Telemetry()
    idx = _fill_two_tenants(_index(tel))
    res = idx.search_fused_requests(
        [RetrievalRequest(query=_basis(0), tenant="a", k=16),
         RetrievalRequest(query=_basis(8), tenant="b", k=4)], **_KW)
    assert len(res[0].ids) == 8            # tenant a owns 8 non-super rows
    assert len(res[1].ids) == 4
    assert tel.counter_total("device.topk_shortfall") == 16 - 8


def test_ingest_counters_ride_the_readback():
    tel = Telemetry()
    idx = _index(tel)
    ids = [f"n{i}" for i in range(6)]
    # one tight cluster: every pairwise similarity clears the 0.5 link
    # gate, so the device-side accepted-link counter must see real work
    rng = np.random.default_rng(7)
    emb = (_basis(0)[None, :]
           + 0.05 * rng.standard_normal((6, D))).astype(np.float32)
    idx.add([f"seed{i}" for i in range(4)], emb[:4], [0.5] * 4, [0.0] * 4,
            ["semantic"] * 4, ["default"] * 4, "u")
    _, _, created = idx.ingest_batch(
        ids, emb, [0.5] * 6, [0.0] * 6, ["semantic"] * 6,
        ["default"] * 6, "u")
    n_created = sum(len(v) for v in created.values())
    assert n_created >= 1
    assert tel.counter_total("ingest.dispatches") == 1
    # device truth ≥ host-registered edges (the device count includes
    # accepted links whose (src, tgt) key the host already knew)
    assert tel.counter_total("ingest.links_accepted") >= n_created
    assert tel.counter_total("ingest.pool_slots_used") >= 1


def test_sharded_serve_reports_counters_and_spans():
    """The pod path (ONE distributed dispatch) reports the same device
    counters and host spans as the single-chip paths, and its dispatch
    count reaches the registry (satellite: it used to be visible only by
    wrapping the ``_dispatch`` hook)."""
    import jax

    from lazzaro_tpu.parallel.index import ShardedMemoryIndex
    from lazzaro_tpu.parallel.mesh import make_mesh

    tel = Telemetry()
    mesh = make_mesh(("data",), (2,), devices=jax.devices()[:2])
    idx = ShardedMemoryIndex(mesh, dim=D, capacity=127, dtype=np.float32,
                             telemetry=tel)
    idx.add(["s0"], _basis(0).reshape(1, -1), "u", supers=[True])
    idx.add([f"m{i}" for i in range(6)],
            np.stack([_basis(1 + i) for i in range(6)]), "u")
    res = idx.serve_requests([
        RetrievalRequest(query=_basis(0), tenant="u", k=4,
                         gate_enabled=True, boost=True),
        RetrievalRequest(query=_basis(3), tenant="u", k=4,
                         gate_enabled=True, boost=True)])
    assert res[0].fast and not res[1].fast
    assert idx.dispatch_count == 1
    assert tel.counter_total("serve.dispatches") == 1
    assert tel.timer_count("serve.dispatch_ms") == 1
    assert tel.timer_count("serve.decode_ms") == 1
    assert tel.counter_total("device.gate_hit") == 1
    assert tel.counter_total("device.gate_miss") == 1
    # only the gate-miss query boosts: min(cap_take=5, live=6) rows
    assert tel.counter_total("device.boost_rows") == 5
    assert tel.counter_total("device.topk_shortfall") == 0
    assert tel.counter_total("serve.live_requests") == 2


# ---------------------------------------------------- zero extra dispatches
def test_telemetry_adds_zero_dispatches(monkeypatch):
    """With telemetry ON (the default) and visibly recording, a chat turn
    still costs exactly ONE fused dispatch and a query-cache hit stays
    zero-RTT — observability is bytes on an existing readback, never an
    extra device program."""
    with tempfile.TemporaryDirectory() as tmp:
        ms = _ingest(_system(tmp))
        assert ms.telemetry.enabled
        ms.start_conversation()
        calls = _count_dispatches(monkeypatch)
        ms.chat("fact 7 body")
        assert calls["search_fused_ragged"] == 1
        assert sum(calls.values()) == 1
        # the turn actually landed in the registry (spans + device tail)
        assert ms.telemetry.counter_total("serve.dispatches") == 1
        assert ms.telemetry.timer_count("serve.dispatch_ms") == 1
        assert ms.telemetry.timer_count("serve.queue_wait_ms") >= 1
        ms.chat("fact 7 body")             # query-cache hit
        assert sum(calls.values()) == 1    # STILL one: cached turn = 0
        assert ms.telemetry.counter_total("serve.dispatches") == 1
        ms.close()


# ------------------------------------------------------- exposure surfaces
def test_metrics_endpoint_matches_summary():
    """Acceptance: the dashboard's ``/metrics`` Prometheus gauges and the
    ``/api/metrics`` JSON must agree with MemorySystem.metrics_summary()."""
    from lazzaro_tpu.dashboard.api import make_server

    with tempfile.TemporaryDirectory() as tmp:
        ms = _ingest(_system(tmp))
        ms.start_conversation()
        ms.chat("fact 7 body")
        ms.search_memories("fact 3 body")
        server = make_server(ms, "127.0.0.1", 0)
        port = server.server_address[1]
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/api/metrics") as r:
                api = json.loads(r.read())
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics") as r:
                assert r.headers["Content-Type"].startswith("text/plain")
                text = r.read().decode()
            summary = ms.metrics_summary()
        finally:
            server.shutdown()
            t.join(timeout=10)
            ms.close()

        # JSON surface == metrics_summary (same registry, same derivation)
        assert api["serve_dispatches"] == summary["serve_dispatches"]
        assert api["pad_waste_fraction"] == summary["pad_waste_fraction"]
        assert api["telemetry"]["counters"] == \
            summary["telemetry"]["counters"]

        # Prometheus surface: per-label counter samples sum to the
        # summary's totals, and the derived headline gauges match
        prom = {}
        for line in text.splitlines():
            if line.startswith("#") or not line.strip():
                continue
            key, val = line.rsplit(" ", 1)
            prom[key] = float(val)
        dispatched = sum(v for k, v in prom.items()
                         if k.startswith("lazzaro_serve_dispatches_total"))
        assert dispatched == summary["serve_dispatches"] > 0
        assert prom["lazzaro_pad_waste_fraction"] == \
            pytest.approx(summary["pad_waste_fraction"])
        assert prom["lazzaro_queue_wait_ms_p50"] == \
            pytest.approx(summary["queue_wait_ms_p50"])
        # the device-counter tail reached the API (the chat turn boosts
        # its retrieved rows, counted ON DEVICE in the readback tail)
        assert summary["telemetry"]["counters"]["device.boost_rows"] >= 1


def test_tier_gauges_on_every_surface():
    """ISSUE 8 satellite: the tier gauges (tier.hot_rows / tier.cold_rows
    / tier.cold_hit_rate / tier.pump_chunk_ms) land in the registry and
    surface through metrics_summary(), the Prometheus ``/metrics`` text
    AND the JSON ``/api/metrics`` — the endpoint-parity contract extended
    to the tiered-memory subsystem."""
    from lazzaro_tpu.dashboard.api import make_server

    with tempfile.TemporaryDirectory() as tmp:
        ms = _system(tmp)
        ms.config.tier_hot_budget_rows = 8
        tmgr = ms.index.enable_tiering(8, hysteresis_s=0.0)
        _ingest(ms, convs=3)
        rows = [r for r in ms.index.row_to_id][:6]
        tmgr.demote_rows(rows)
        ms.chat("conv 1")                 # serving feeds cold_hit_rate
        summary = ms.metrics_summary()
        assert summary["tier"]["cold_rows"] == tmgr.cold_count > 0
        assert summary["tier"]["hot_rows"] == tmgr.hot_rows
        gauges = summary["telemetry"]["gauges"]
        for name in ("tier.hot_rows", "tier.cold_rows",
                     "tier.cold_hit_rate", "tier.pump_chunk_ms"):
            assert name in gauges, name
        assert gauges["tier.cold_rows"] == tmgr.cold_count

        server = make_server(ms, "127.0.0.1", 0)
        port = server.server_address[1]
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/api/metrics") as r:
                api = json.loads(r.read())
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics") as r:
                text = r.read().decode()
        finally:
            server.shutdown()
            t.join(timeout=10)
            ms.close()
        assert api["tier"]["cold_rows"] == summary["tier"]["cold_rows"]
        assert api["telemetry"]["gauges"]["tier.cold_rows"] == \
            gauges["tier.cold_rows"]
        assert f"lazzaro_tier_cold_rows {float(tmgr.cold_count)}" in text
        assert "lazzaro_tier_hot_rows" in text
        assert "lazzaro_tier_cold_hit_rate" in text


def test_metrics_summary_shape():
    with tempfile.TemporaryDirectory() as tmp:
        ms = _ingest(_system(tmp))
        ms.search_memories("fact 3 body")
        s = ms.metrics_summary()
        json.dumps(s)                      # JSON-able end to end
        assert 0.0 <= s["pad_waste_fraction"] < 1.0
        assert s["serve_dispatches"] >= 1
        assert s["ingest_dispatches"] >= 1
        assert s["scheduler"]["requests_served"] >= 1
        assert "device.gate_hit" not in s["telemetry"]["timers"]
        ms.close()


def test_counters_survive_checkpoint_roundtrip():
    """Satellite: ``link_pool_overflows`` used to silently reset on
    checkpoint load; it must survive the round trip now."""
    from lazzaro_tpu.core.checkpoint import load_index, save_index

    idx = _fill_two_tenants(_index())
    idx.link_pool_overflows = 3
    with tempfile.TemporaryDirectory() as tmp:
        save_index(idx, tmp)
        back = load_index(tmp)
    assert back.link_pool_overflows == 3
