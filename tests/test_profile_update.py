"""Deep consolidation: seeded graph → run_consolidation → profile domains
updated via prompt-sniffing fake LLM (reference test_profile_update.py
pattern, SURVEY §4)."""

import json

import numpy as np
import pytest

from lazzaro_tpu import MemorySystem
from lazzaro_tpu.models.graph import Edge, Node

from tests.fakes import MockEmbedder, MockLLM

INSIGHTS = {
    "preferences": "User prefers Python for data work.",
    "personality_traits": "User is methodical.",
    "knowledge_domains": "Strong grasp of memory systems.",
    "interaction_style": "Concise and technical.",
}


@pytest.fixture()
def ms(tmp_db):
    llm = MockLLM(sniffers={
        "Analyze these related memories": json.dumps(INSIGHTS),
    })
    system = MemorySystem(enable_async=False, auto_consolidate=False,
                          load_from_disk=False, db_dir=tmp_db,
                          llm_provider=llm, embedding_provider=MockEmbedder(),
                          verbose=False)
    yield system
    system.close()


def seed_component(ms, n=3, weight=0.8):
    """n nodes chained with strong edges in one shard."""
    shard = ms._get_or_create_shard("work")
    for i in range(n):
        emb = np.zeros(8, np.float32)
        emb[i % 8] = 1.0
        node = Node(id=f"node_{i}", content=f"Memory about project phase {i}",
                    embedding=emb.tolist(), shard_key="work")
        shard.add_node(node)
        ms._index_add_node(node)
    for i in range(n - 1):
        ms._add_edge(Edge(source=f"node_{i}", target=f"node_{i+1}", weight=weight))


def test_component_profile_extraction(ms):
    seed_component(ms, n=3, weight=0.8)
    result = ms.run_consolidation(merge_similar=False)
    assert "Updated" in result
    for domain, insight in INSIGHTS.items():
        assert ms.profile.data[domain] == insight


def test_small_components_fall_back_to_whole_graph(ms):
    seed_component(ms, n=2, weight=0.8)  # below component_min_size
    seed_component_extra = Node(id="node_x", content="Isolated fact",
                                embedding=[0, 0, 0, 0, 0, 0, 0, 1.0])
    ms._get_or_create_shard("personal").add_node(seed_component_extra)
    ms._index_add_node(seed_component_extra)
    result = ms.run_consolidation(merge_similar=False)
    # fallback whole-graph extraction fires (≥3 total contents)
    assert "Updated" in result


def test_weak_components_skip_profile(ms):
    seed_component(ms, n=3, weight=0.2)  # below avg-weight gate 0.3
    ms.run_consolidation(merge_similar=False)
    # component skipped, but whole-graph fallback still updates
    assert ms.profile.data["preferences"] == INSIGHTS["preferences"]


def test_merge_similar_nodes_all_pairs(ms):
    shard = ms._get_or_create_shard("work")
    dup = [1.0, 0, 0, 0, 0, 0, 0, 0]
    for i, nid in enumerate(["node_1", "node_2", "node_3"]):
        node = Node(id=nid, content=f"dup {i}", embedding=list(dup),
                    shard_key="work")
        shard.add_node(node)
        ms._index_add_node(node)
    distinct = Node(id="node_9", content="distinct",
                    embedding=[0, 1.0, 0, 0, 0, 0, 0, 0], shard_key="work")
    shard.add_node(distinct)
    ms._index_add_node(distinct)

    merged = ms._merge_similar_nodes(0.95)
    assert merged == 2  # node_2 and node_3 absorbed into node_1
    nodes, _ = ms.buffer.size()
    assert nodes == 2
    keeper = ms.buffer.get_node("node_1")
    assert "dup 1" in keeper.content and "dup 2" in keeper.content


def test_profile_context_rendering(ms):
    ms.profile.update_domain("preferences", "Tea over coffee")
    ctx = ms.profile.get_context()
    assert "Preferences: Tea over coffee" in ctx
    assert ms.profile.update_domain("not_a_domain", "x") is False
