"""IVF-PQ serving kernels (ops/pq.py): codebook quality, recall vs the
exact oracle, refinement exactness, and masking."""

import numpy as np
import jax.numpy as jnp

from lazzaro_tpu.ops.ivf import build_ivf
from lazzaro_tpu.ops.pq import PQCodebook, encode_pq, ivf_pq_search, train_pq


def _clustered(n, d, group=4, seed=0):
    """Bench-like geometry: groups of `group` rows at ~0.88 cosine."""
    rng = np.random.default_rng(seed)
    n_groups = n // group
    g_dirs = rng.standard_normal((n_groups, d)).astype(np.float32)
    g_dirs /= np.linalg.norm(g_dirs, axis=1, keepdims=True)
    noise = rng.standard_normal((n, d)).astype(np.float32)
    noise /= np.linalg.norm(noise, axis=1, keepdims=True)
    v = 0.94 * g_dirs[np.arange(n) % n_groups] + 0.35 * noise
    return (v / np.linalg.norm(v, axis=1, keepdims=True)).astype(np.float32)


def test_codebook_reconstruction_error():
    d = 64
    emb = _clustered(4096, d)
    book = train_pq(jnp.asarray(emb), np.ones((4096,), bool), m=d // 8,
                    iters=10, seed=1)
    codes = np.asarray(encode_pq(book.centroids, jnp.asarray(emb)))
    assert codes.shape == (4096, d // 8) and codes.dtype == np.uint8
    cent = np.asarray(book.centroids)                      # [m, 256, dsub]
    recon = cent[np.arange(d // 8)[None, :], codes]        # [N, m, dsub]
    recon = recon.reshape(4096, d)
    cos = (recon * emb).sum(1) / np.maximum(
        np.linalg.norm(recon, axis=1), 1e-9)
    # PQ is lossy by design (~0.88 cosine at dsub=8/256 centroids on this
    # geometry); serving recall comes from the shortlist + exact refine,
    # gated by the recall test below — this only guards against a broken
    # codebook (random codes sit near 0)
    assert cos.mean() > 0.8, f"mean reconstruction cosine {cos.mean():.3f}"


def test_ivf_pq_recall_and_exact_scores():
    n, d, k = 20000, 64, 5
    emb = _clustered(n, d, seed=2)
    mask = np.ones((n,), bool)
    dev = jnp.asarray(emb)
    ivf = build_ivf(dev, mask, n_clusters=64, seed=3)
    book = train_pq(dev, mask, iters=10, seed=4)
    codes = encode_pq(book.centroids, dev)

    rng = np.random.default_rng(5)
    qrows = rng.integers(0, n, size=48)
    queries = emb[qrows]

    # exact oracle top-k
    oracle_scores = queries @ emb.T
    oracle = np.argsort(-oracle_scores, axis=1)[:, :k]

    s, rows = ivf_pq_search(ivf.centroids, ivf.members, ivf.residual,
                            book.centroids, codes, dev, jnp.asarray(mask),
                            jnp.asarray(queries), k, nprobe=8, r=64)
    s, rows = np.asarray(s), np.asarray(rows)

    recall = np.mean([len(set(rows[i]) & set(oracle[i])) / k
                      for i in range(len(qrows))])
    assert recall > 0.9, f"ivf-pq recall@5 {recall:.3f}"

    # refinement exactness: every returned score equals the EXACT cosine
    # of that row (the PQ approximation only picks the shortlist)
    for i in range(len(qrows)):
        for j in range(k):
            if s[i, j] < -1e29:
                continue
            exact = float(oracle_scores[i, rows[i, j]])
            assert abs(s[i, j] - exact) < 5e-3
    # self-query: top-1 is the row itself at ~1.0
    assert (rows[:, 0] == qrows).mean() > 0.95


def test_ivf_pq_respects_mask():
    n, d = 8192, 32
    emb = _clustered(n, d, seed=6)
    mask = np.ones((n,), bool)
    dead = np.arange(0, n, 3)
    mask[dead] = False
    dev = jnp.asarray(emb)
    ivf = build_ivf(dev, np.ones((n,), bool), n_clusters=32, seed=7)
    book = train_pq(dev, mask, iters=6, seed=8)
    codes = encode_pq(book.centroids, dev)
    q = emb[dead[:8]]                     # query WITH dead rows' vectors
    _, rows = ivf_pq_search(ivf.centroids, ivf.members, ivf.residual,
                            book.centroids, codes, dev, jnp.asarray(mask),
                            jnp.asarray(q), 5, nprobe=8, r=64)
    rows = np.asarray(rows)
    dead_set = set(dead.tolist())
    assert not any(int(r) in dead_set for r in rows.ravel() if r >= 0)


def test_memory_index_pq_serving_and_freshness():
    from lazzaro_tpu.core.index import MemoryIndex

    rng = np.random.default_rng(10)
    d, n = 32, 5000                       # past _IVF_MIN_ROWS
    emb = _clustered(n, d, seed=11)
    idx = MemoryIndex(dim=d, capacity=n + 64, ivf_nprobe=8, pq_serving=True)
    assert idx.pq_serving
    ids = [f"m{i}" for i in range(n)]
    idx.add(ids, emb, [0.5] * n, [0.0] * n, ["semantic"] * n,
            ["default"] * n, "u1")
    assert idx.ivf_maintenance()          # builds IVF AND trains the book
    assert idx._pq_book is not None

    probe = rng.integers(0, n, 50)
    res = idx.search_batch(emb[probe], "u1", k=1)
    assert idx._pq_codes is not None      # the PQ path actually served
    hits = sum(1 for p, (got, _) in zip(probe, res) if got == [f"m{p}"])
    assert hits >= 47, f"pq self-recall {hits}/50"
    # refinement exactness: the self-hit score is the exact cosine (~1.0)
    (got, sc), = idx.search_batch(emb[probe[:1]], "u1", k=1)
    assert abs(sc[0] - 1.0) < 5e-3

    # a fresh post-build row gets its codes PATCHED into the published
    # pack at write time (ISSUE 16: no dirty flag, no offline re-encode)
    fresh = np.zeros((1, d), np.float32)
    fresh[0, 3] = 1.0
    idx.add(["fresh"], fresh, [0.5], [0.0], ["semantic"], ["default"], "u1")
    pack = idx._pq_pack
    assert pack is not None and pack[1] is not None   # still complete
    frow = idx.id_to_row["fresh"]
    want = np.asarray(encode_pq(pack[0].centroids,
                                idx.state.emb[frow:frow + 1]))[0]
    assert np.array_equal(np.asarray(pack[1])[frow], want)
    (got, _), = idx.search_batch(fresh, "u1", k=1)
    assert got == ["fresh"]

    # exact=True bypasses the whole approximate stack
    (got_exact, _), = idx.search_batch(fresh, "u1", k=1, exact=True)
    assert got_exact == ["fresh"]

    assert ", pq" in idx.stats()["ivf"]


def test_pq_without_ivf_is_inert():
    from lazzaro_tpu.core.index import MemoryIndex

    idx = MemoryIndex(dim=16, capacity=64, pq_serving=True)  # no ivf_nprobe
    assert not idx.pq_serving


def test_system_pq_maintenance_and_snapshot(tmp_path):
    """MemorySystem threads pq_serving through construction, the worker
    maintenance hook, and snapshot restore (the ivf_serving restore drop
    was advisor r4's medium finding — PQ must not repeat it)."""
    from lazzaro_tpu.config import MemoryConfig
    from lazzaro_tpu.core.memory_system import MemorySystem

    ms = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db"),
                      verbose=False, load_from_disk=False,
                      config=MemoryConfig(journal=False, ivf_serving=4,
                                          pq_serving=True))
    assert ms.index.pq_serving
    ms.start_conversation()
    ms.chat("I work as a data engineer on a big ETL project.")
    ms.end_conversation()
    snap = str(tmp_path / "snap")
    ms.save_snapshot(snap)
    ms.close()

    ms2 = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db2"),
                       verbose=False, load_from_disk=False,
                       config=MemoryConfig(journal=False, ivf_serving=4,
                                           pq_serving=True))
    assert "loaded" in ms2.load_snapshot(snap)
    assert ms2.index.pq_serving and ms2.index.ivf_nprobe == 4
    assert ms2.search_memories("what is the user's job?")
    ms2.close()


def test_pq_codes_never_published_against_newer_book():
    """A reader that re-encodes codes for an OLD book while maintenance
    already published a new one must not overwrite the new pack — codes
    are meaningless against any other book (r5 review)."""
    from lazzaro_tpu.core.index import MemoryIndex
    from lazzaro_tpu.ops.pq import train_pq

    d, n = 32, 5000
    emb = _clustered(n, d, seed=20)
    idx = MemoryIndex(dim=d, capacity=n + 64, ivf_nprobe=8, pq_serving=True)
    idx.add([f"m{i}" for i in range(n)], emb, [0.5] * n, [0.0] * n,
            ["semantic"] * n, ["default"] * n, "u1")
    assert idx.ivf_maintenance()
    old_pack = idx._pq_pack

    # simulate a maintenance retrain racing a reader that still holds a
    # CODELESS old pack (the pre-ISSUE-16 lazy shape; today only a pack
    # caught mid-publish looks like this)
    old_pack = (old_pack[0], None)
    new_book = train_pq(idx.state.emb, np.ones((idx.state.emb.shape[0],),
                                               bool), seed=99)
    idx._pq_pack = (new_book, None)
    new_pack = idx._pq_pack

    codes = idx._pq_codes_for(idx.state, old_pack)   # reader with old pack
    assert codes is not None
    assert idx._pq_pack is new_pack                  # not overwritten
    assert idx._pq_pack[1] is None                   # new book still codeless
