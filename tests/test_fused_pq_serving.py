"""Fused IVF-PQ serving (ISSUE 16; tier-1 smoke, CPU, small arenas).

The last serving mode outside the one-dispatch turn: with a COMPLETE
``(codebook, codes)`` pack published by maintenance, the chat turn's whole
retrieval — ADC table build, m-byte PQ member scan over the top-nprobe
clusters, exact f32 shortlist rescore at ``coarse_fetch_slack``, super
gate / CSR gather / boost tail — runs as ONE device program
(``state.search_fused_pq[_ragged]`` + ``_copy``/``_read`` twins). These
tests pin:

- the jit counters: ONE PQ dispatch per chat turn, the read twin for pure
  reads, ZERO dispatches on cached turns;
- recall@10 against the classic multi-dispatch ``ivf_pq_search`` path on
  a clustered 10k fixture at nprobe ∈ {4, 8};
- gate-verdict parity with the classic path (the 0.4 super-gate decision
  comes from the exact rescore, never the ADC approximation) across
  gate-hit and gate-miss turns, boost columns included;
- incremental codes: the fused ingest's in-kernel ``_pq_scatter`` keeps
  the pack current at ZERO added dispatches (no offline ``encode_pq``
  pass, no dirty flag anywhere);
- PQ × tiering: demote → serve → promote round-trips through the
  ``pq_tiered`` cold-shadow scan with no dense fallback;
- 2-way mesh parity: the row-sharded PQ member scan returns the same
  rows/scores as the sharded IVF exact scan over the same tables;
- checkpoint round trip: codebook + codes + the dirty-free invariant
  survive ``checkpoint.save_index``/``load_index``;
- member-table hole re-pack reclaims delete/demote holes and bumps
  ``ivf.member_repacks`` (satellite).
"""

import tempfile

import numpy as np
import pytest

import jax

from lazzaro_tpu.config import MemoryConfig
from lazzaro_tpu.core import state as S
from lazzaro_tpu.core.index import MemoryIndex
from lazzaro_tpu.core.memory_system import MemorySystem
from lazzaro_tpu.serve import RetrievalRequest
from tests.test_fused_ingest import ClusteredEmb, QueueLLM

D = 24
KW = dict(cap_take=5, max_nbr=8, super_gate=0.4, acc_boost=0.05,
          nbr_boost=0.02)


def _system(tmp, serve_fused=True, nprobe=4, per=20, super_threshold=100):
    ms = MemorySystem(
        enable_async=False, db_dir=tmp, verbose=False, load_from_disk=False,
        llm_provider=QueueLLM(per), embedding_provider=ClusteredEmb(),
        auto_prune=False, max_buffer_size=10_000,
        super_node_threshold=super_threshold,
        config=MemoryConfig(journal=False, auto_consolidate=False,
                            decay_rate=0.0, ivf_serving=nprobe,
                            pq_serving=True,
                            # tiny tier-1 arenas: the ragged k ceiling must
                            # stay below the visited-candidate count or the
                            # PQ pack falls back to the dense scan
                            serve_k_max=16))
    ms.config.serve_fused = serve_fused
    return ms


def _ingest_built(ms, convs=2):
    for c in range(convs):
        ms.start_conversation()
        ms.add_to_short_term(f"conv {c}", "episodic", 0.7)
        ms.end_conversation()
    ms.index._IVF_MIN_ROWS = 1
    assert ms.index.ivf_maintenance()      # builds IVF AND the PQ pack
    assert ms.index._pq_pack is not None and ms.index._pq_pack[1] is not None
    return ms


_COUNTED = ("search_fused_pq", "search_fused_pq_copy",
            "search_fused_pq_read", "search_fused_pq_ragged",
            "search_fused_pq_ragged_copy", "search_fused_pq_ragged_read",
            "search_fused_ivf", "search_fused_ivf_copy",
            "search_fused_ivf_read", "search_fused_ivf_ragged",
            "search_fused_ivf_ragged_copy", "search_fused_ivf_ragged_read",
            "search_fused_quant", "search_fused_quant_copy",
            "search_fused_quant_read", "search_fused_quant_ragged",
            "search_fused_quant_ragged_copy",
            "search_fused_quant_ragged_read", "search_fused",
            "search_fused_copy", "search_fused_read", "search_fused_ragged",
            "search_fused_ragged_copy", "search_fused_ragged_read",
            "arena_search", "arena_update_access",
            "arena_update_access_copy", "arena_boost", "arena_boost_copy",
            "arena_apply_boosts", "arena_apply_boosts_copy")


def _count_dispatches(monkeypatch, names=_COUNTED):
    calls = {name: 0 for name in names}
    for name in names:
        orig = getattr(S, name)

        def wrapped(*a, __orig=orig, __name=name, **kw):
            calls[__name] += 1
            return __orig(*a, **kw)

        monkeypatch.setattr(S, name, wrapped)
    return calls


# ------------------------------------------------------------ jit counters
def test_one_pq_dispatch_per_chat_turn(monkeypatch):
    """A chat turn with a published PQ pack costs exactly ONE device
    dispatch — the donated ``search_fused_pq_ragged`` program — and zero
    IVF/quant/dense/classic search or boost dispatches."""
    with tempfile.TemporaryDirectory() as tmp:
        ms = _ingest_built(_system(tmp))
        ms.start_conversation()
        ms.chat("fact 3 body")             # warm: compiles the kernel
        calls = _count_dispatches(monkeypatch)
        ms.chat("fact 7 body")
        assert calls["search_fused_pq_ragged"] == 1
        for name in calls:
            if name != "search_fused_pq_ragged":
                assert calls[name] == 0, (name, calls)
        ms.close()


def test_pq_search_memories_takes_readonly_twin(monkeypatch):
    """A pure read batch takes ``search_fused_pq_ragged_read`` — same ADC
    member scan, no donation dance, ONE dispatch per coalesced batch."""
    with tempfile.TemporaryDirectory() as tmp:
        ms = _ingest_built(_system(tmp))
        ms.search_memories("fact 1 body")  # warm the kernel
        calls = _count_dispatches(monkeypatch)
        hits = ms.search_memories("fact 3 body")
        assert hits
        assert calls["search_fused_pq_ragged_read"] == 1
        assert calls["search_fused_pq_ragged"] == 0
        ms.search_memories_batch([f"fact {i} body" for i in range(8)])
        assert calls["search_fused_pq_ragged_read"] == 2
        ms.close()


def test_pq_cached_hit_turn_pays_zero_dispatches(monkeypatch):
    """Zero-RTT query-cache hits survive PQ mode: a cached turn queues
    boost counts host-side and the flush stays ONE scatter."""
    with tempfile.TemporaryDirectory() as tmp:
        ms = _ingest_built(_system(tmp))
        ms.start_conversation()
        ms.chat("fact 7 body")             # populates the query cache
        calls = _count_dispatches(monkeypatch)
        ms.chat("fact 7 body")             # cache hit
        for name in calls:
            assert calls[name] == 0, (name, calls)
        assert ms._pending_boosts
        ms.end_conversation()
        assert calls["arena_apply_boosts"] == 1
        ms.close()


# ------------------------------------------------------------------ recall
def _clustered_fixture(n=10_000, d=48, n_centers=64, seed=42, spread=0.5):
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((n_centers, d)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    lbl = rng.integers(0, n_centers, n)
    emb = centers[lbl] + (spread / np.sqrt(d)) * rng.standard_normal(
        (n, d)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    return rng, emb


def _recall(result_rows, truth_rows, k):
    hits = sum(len(set(r) & set(t[:k])) for r, t in
               zip(result_rows, truth_rows))
    return hits / (k * len(result_rows))


@pytest.mark.parametrize("nprobe", [4, 8])
def test_fused_pq_recall_parity_with_classic_pq_10k(nprobe):
    """recall@10 vs the exact ranking on a clustered 10k fixture: the
    fused single-dispatch PQ path must hold its own against the classic
    multi-dispatch ``ivf_pq_search`` routing (``search_batch``). Both
    scan the same m-byte codes over the same candidate set and rescore
    exactly; the classic path refines a deeper shortlist (r=128 vs
    k+slack), so the fused path gets a small allowance."""
    n, d, k, nq = 10_000, 48, 10, 64
    rng, emb = _clustered_fixture(n=n, d=d)
    idx = MemoryIndex(dim=d, capacity=n + 64, ivf_nprobe=nprobe,
                      pq_serving=True, coarse_slack=32)
    idx.add([f"m{i}" for i in range(n)], emb, [0.5] * n, [0.0] * n,
            ["semantic"] * n, ["default"] * n, "u0")
    assert idx.ivf_maintenance()
    assert idx._pq_pack is not None and idx._pq_pack[1] is not None
    base = rng.integers(0, n, size=nq)
    queries = emb[base] + (0.3 / np.sqrt(d)) * rng.standard_normal(
        (nq, d)).astype(np.float32)
    queries /= np.linalg.norm(queries, axis=1, keepdims=True)
    truth = np.argsort(-(queries @ emb.T), axis=1)[:, :k]

    classic = idx.search_batch(queries, "u0", k=k)   # classic ivf_pq_search
    classic_rows = [[idx.id_to_row[i] for i in ids_] for ids_, _ in classic]

    reqs = [RetrievalRequest(query=queries[i], tenant="u0", k=k)
            for i in range(nq)]
    fused = idx.search_fused_requests(reqs, **KW)
    fused_rows = [[idx.id_to_row[i] for i in r.ids] for r in fused]

    r_classic = _recall(classic_rows, truth, k)
    r_fused = _recall(fused_rows, truth, k)
    assert r_fused >= 0.9, (r_fused, r_classic)
    assert r_fused >= r_classic - 0.03, (r_fused, r_classic)
    for rows in fused_rows:                # in-kernel dedup: no duplicates
        assert len(rows) == len(set(rows))
    # exact rescore: self-queries return the row itself at ~1.0
    self_reqs = [RetrievalRequest(query=emb[i], tenant="u0", k=1)
                 for i in range(8)]
    res = idx.search_fused_requests(self_reqs, **KW)
    for i, r in enumerate(res):
        assert r.ids[0] == f"m{i}"
        assert abs(r.scores[0] - 1.0) < 5e-3


# ----------------------------------------------------- gate-verdict parity
def _numeric_cols(ms):
    cols = ms.index.pull_numeric()
    n = len(ms.index.id_to_row)
    return {k: cols[k][: n + 2] for k in ("salience", "access_count")}


def test_pq_matches_classic_chat_turns():
    """Gate-miss parity: ids and boost side effects (salience + access
    counts on the arena AND host copies) match the classic multi-dispatch
    PQ serving path — including repeated (cached) turns. Both paths'
    verdicts come from the exact rescore, so ADC error never shows."""
    a = _ingest_built(_system(tempfile.mkdtemp(), serve_fused=True))
    b = _ingest_built(_system(tempfile.mkdtemp(), serve_fused=False))
    try:
        a.start_conversation()
        b.start_conversation()
        for q in ("fact 3 body", "fact 17 body", "fact 31 body",
                  "fact 3 body"):          # last one is a cache hit
            ra = a.chat(q)
            rb = b.chat(q)
            assert ra == rb
        a.end_conversation()
        b.end_conversation()
        ca, cb = _numeric_cols(a), _numeric_cols(b)
        np.testing.assert_allclose(ca["salience"], cb["salience"], atol=1e-6)
        np.testing.assert_array_equal(ca["access_count"], cb["access_count"])
    finally:
        a.close()
        b.close()


def test_pq_matches_classic_super_gate_hit():
    """Gate-hit parity: the extras array carries EVERY super row and the
    gate top-1 score is the exact rescore — the device skips boosts
    exactly when the classic exact gate search would have fired."""
    def build(serve_fused):
        ms = _ingest_built(_system(tempfile.mkdtemp(),
                                   serve_fused=serve_fused,
                                   super_threshold=5))
        assert ms.super_nodes
        return ms

    a, b = build(True), build(False)
    try:
        sid = sorted(a.super_nodes)[0]
        centroid = np.asarray(a.super_nodes[sid].embedding, np.float32)
        ids_a, mode_a = a._retrieve_for_chat(centroid.tolist(), "probe-q")
        ids_b, mode_b = b._retrieve_for_chat(centroid.tolist(), "probe-q")
        assert ids_a == ids_b
        assert mode_a == "classic"         # device skipped boosts
        assert mode_b == "classic"
        a.start_conversation()
        b.start_conversation()
        a.chat("fact 5 body")
        b.chat("fact 5 body")
        ca, cb = _numeric_cols(a), _numeric_cols(b)
        np.testing.assert_allclose(ca["salience"], cb["salience"], atol=1e-6)
        np.testing.assert_array_equal(ca["access_count"], cb["access_count"])
    finally:
        a.close()
        b.close()


# ------------------------------------------------------- incremental codes
_INGEST_COUNTED = ("ingest_dedup_fused", "ingest_dedup_fused_copy",
                   "arena_add", "arena_add_copy", "arena_merge_touch",
                   "arena_merge_touch_copy", "edges_add", "edges_add_copy",
                   "arena_search", "ivf_members_drop",
                   "ivf_members_drop_copy")


def test_incremental_codes_add_zero_ingest_dispatches(monkeypatch):
    """The in-kernel ``_pq_scatter`` keeps the pack current: one fused
    ingest mega-batch with a live PQ pack is STILL one dispatch (no
    offline ``encode_pq`` kernel beside it), and the new rows' codes land
    bit-identical to a from-scratch encode of the stored vectors."""
    from lazzaro_tpu.ops.pq import encode_pq

    n, d = 5_000, 32
    rng, emb = _clustered_fixture(n=n, d=d, seed=3)
    idx = MemoryIndex(dim=d, capacity=n + 512, ivf_nprobe=4,
                      pq_serving=True)
    idx.add([f"m{i}" for i in range(n)], emb, [0.5] * n, [0.0] * n,
            ["semantic"] * n, ["default"] * n, "u0")
    assert idx.ivf_maintenance()
    batch = emb[:16] + 0.1 * rng.standard_normal((16, d)).astype(np.float32)
    batch /= np.linalg.norm(batch, axis=1, keepdims=True)
    # warm the ingest kernel geometry first, then count
    pend = idx.ingest_batch_dedup(batch[:8], [0.5] * 8, [1.0] * 8,
                                  ["semantic"] * 8, ["s"] * 8, "u0",
                                  dedup_gate=1.01)
    idx.commit_ingest_dedup(pend, [f"w{i}" for i in range(8)])
    calls = _count_dispatches(monkeypatch, _INGEST_COUNTED)
    pend = idx.ingest_batch_dedup(batch[8:], [0.5] * 8, [1.0] * 8,
                                  ["semantic"] * 8, ["s"] * 8, "u0",
                                  dedup_gate=1.01)
    idx.commit_ingest_dedup(pend, [f"x{i}" for i in range(8)])
    assert calls["ingest_dedup_fused"] == 1
    for name in calls:
        if name != "ingest_dedup_fused":
            assert calls[name] == 0, (name, calls)
    pack = idx._pq_pack
    assert pack is not None and pack[1] is not None   # still complete
    rows = np.asarray([idx.id_to_row[f"x{i}"] for i in range(8)])
    want = np.asarray(encode_pq(pack[0].centroids, idx.state.emb[rows]))
    assert np.array_equal(np.asarray(pack[1])[rows], want)
    # and the fresh rows serve through the fused PQ path
    reqs = [RetrievalRequest(query=batch[8 + i], tenant="u0", k=3)
            for i in range(8)]
    res = idx.search_fused_requests(reqs, **KW)
    for i, r in enumerate(res):
        assert r.ids and r.ids[0] == f"x{i}"


# ------------------------------------------------------------- PQ × tiering
def _assert_results_equal(a_list, b_list):
    for a, b in zip(a_list, b_list):
        assert a.ids == b.ids
        assert np.allclose(a.scores, b.scores, atol=2e-6)
        assert a.fast == b.fast
        assert a.gate_id == b.gate_id


def test_pq_tiering_demote_promote_round_trip():
    """Mixed hot/cold vs all-hot fused PQ at full probe width: tiering
    swaps the cold coarse scan to the m-byte PQ slab (``pq_tiered``) —
    demoted rows keep serving with exact scores (their codes outlive the
    zeroed master), and a promote restores plain ``pq`` serving."""
    n, d = 4_500, 32
    rng, emb = _clustered_fixture(n=n, d=d, seed=17)

    def build():
        idx = MemoryIndex(dim=d, capacity=5000, ivf_nprobe=4096,
                          pq_serving=True, coarse_slack=64, epoch=1000.0)
        idx.add([f"n{i}" for i in range(n)], emb, [0.5] * n, [0.0] * n,
                ["semantic"] * n, ["default"] * n, "u0")
        assert idx.ivf_maintenance(iters=2)
        return idx

    idx_t, idx_h = build(), build()
    assert idx_t._serve_mode_hint(5, [])[0] == "pq"
    tm = idx_t.enable_tiering(hot_budget_rows=1024, hysteresis_s=0.0)
    cold = [idx_t.id_to_row[f"n{i}"] for i in range(2000, n)]
    assert tm.demote_rows(cold) == len(cold)
    assert idx_t._serve_mode_hint(5, [])[0] == "pq_tiered"

    q = emb[list(range(8)) + list(range(2100, 2108))]
    reqs = [RetrievalRequest(query=q[i], tenant="u0", k=10,
                             gate_enabled=True, boost=False)
            for i in range(len(q))]
    r_t = idx_t.search_fused_requests(reqs, **KW)
    r_h = idx_h.search_fused_requests(reqs, **KW)
    assert any(r.cold_hits > 0 for r in r_t)   # the fixture IS mixed
    _assert_results_equal(r_t, r_h)
    # cold self-queries still land their own row with the exact score
    for i in range(8, 16):
        assert r_t[i].ids[0] == f"n{2100 + (i - 8)}"
        assert abs(r_t[i].scores[0] - 1.0) < 5e-3

    assert tm.promote_rows(cold) == len(cold)
    assert idx_t._serve_mode_hint(5, [])[0] == "pq"
    r_t2 = idx_t.search_fused_requests(reqs, **KW)
    assert all(r.cold_hits == 0 for r in r_t2)
    _assert_results_equal(r_t2, r_h)


# ------------------------------------------------------------- mesh parity
@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs 2 devices")
def test_pq_mesh_2way_parity():
    """Pod PQ serving (row-sharded codes, replicated codebook) vs the
    sharded IVF exact scan over the SAME live tables: both rescore
    exactly, so top-1 must agree everywhere and the top-5 sets can only
    differ where the ADC coarse rank pushes a mid-rank row past the
    slack window."""
    from lazzaro_tpu.parallel.index import ShardedMemoryIndex
    from lazzaro_tpu.parallel.mesh import make_mesh
    from lazzaro_tpu.serve.scheduler import RetrievalRequest as PodReq

    mesh = make_mesh(("data",), (2,), devices=jax.devices()[:2])
    rng, emb = _clustered_fixture(n=400, d=D, n_centers=16, seed=23)
    idx = ShardedMemoryIndex(mesh, dim=D, capacity=1023, dtype=np.float32,
                             pq_serving=True, k=10)
    idx.add([f"m{i}" for i in range(400)], emb, "t")
    assert idx.ivf_build(n_clusters=16, nprobe=8)
    assert idx._pq_pack is not None

    reqs = [PodReq(query=emb[i], tenant="t", k=5) for i in range(16)]
    r_pq = idx.serve_requests(reqs)
    snap = idx.telemetry.snapshot()
    assert any("serve.dispatch_ms" in k_ and "pod_pq" in k_
               for k_ in snap["timers"])    # the PQ mode actually served
    idx.pq_serving = False                  # same tables, IVF exact scan
    r_ivf = idx.serve_requests(reqs)

    overlap = 0
    for a, b in zip(r_pq, r_ivf):
        assert a.ids[0] == b.ids[0]
        assert abs(a.scores[0] - b.scores[0]) < 5e-3
        overlap += len(set(a.ids) & set(b.ids))
    assert overlap >= 0.9 * 5 * len(reqs), overlap


# ------------------------------------------------------ checkpoint parity
def test_checkpoint_pq_roundtrip(tmp_path):
    """Codebook + codes + the dirty-free invariant survive
    ``checkpoint.save_index``/``load_index``: the restored pack is
    bit-identical and COMPLETE (no offline re-encode on load), the meta
    block mirrors the ``counters`` idiom, and the restored index keeps
    maintaining codes incrementally."""
    from lazzaro_tpu.core import checkpoint as C
    from lazzaro_tpu.ops.pq import encode_pq

    n, d = 5_000, 32
    rng, emb = _clustered_fixture(n=n, d=d, seed=29)
    idx = MemoryIndex(dim=d, capacity=n + 64, ivf_nprobe=8, pq_serving=True)
    idx.add([f"m{i}" for i in range(n)], emb, [0.5] * n, [0.0] * n,
            ["semantic"] * n, ["default"] * n, "u0")
    assert idx.ivf_maintenance()
    book0, codes0 = idx._pq_pack
    C.save_index(idx, str(tmp_path / "ckpt"))

    # the meta entry rides next to the counters block
    import json
    vdir = (tmp_path / "ckpt" / (tmp_path / "ckpt" / "CURRENT")
            .read_text().strip())
    meta = json.loads((vdir / "meta.json").read_text())
    assert meta["pq"] == {"m": int(book0.m), "dim": d, "complete": True}
    assert "counters" in meta

    idx2 = C.load_index(str(tmp_path / "ckpt"), ivf_nprobe=8,
                        pq_serving=True)
    pack = idx2._pq_pack
    assert pack is not None and pack[1] is not None   # complete on load
    assert np.array_equal(np.asarray(pack[0].centroids),
                          np.asarray(book0.centroids))
    assert np.array_equal(np.asarray(pack[1]), np.asarray(codes0))

    # restored index serves (after the maintenance pass republishes the
    # coarse tables) and still patches codes at write time — no dirty
    # flag resurrection
    assert idx2.ivf_maintenance()
    res = idx2.search_fused_requests(
        [RetrievalRequest(query=emb[7], tenant="u0", k=3)], **KW)
    assert res[0].ids[0] == "m7"
    fresh = np.zeros((1, d), np.float32)
    fresh[0, 5] = 1.0
    idx2.add(["fresh"], fresh, [0.5], [0.0], ["semantic"], ["default"],
             "u0")
    pack2 = idx2._pq_pack
    frow = idx2.id_to_row["fresh"]
    want = np.asarray(encode_pq(pack2[0].centroids,
                                idx2.state.emb[frow:frow + 1]))[0]
    assert np.array_equal(np.asarray(pack2[1])[frow], want)


# ------------------------------------------------------ member-table repack
def test_member_repack_reclaims_delete_holes():
    """Deleting member rows leaves dead slots behind the per-cluster
    cursors; ``ivf_member_repack`` compacts them in ONE host pass, bumps
    the counters, and the repacked tables keep serving the live rows."""
    n, d = 5_000, 32
    rng, emb = _clustered_fixture(n=n, d=d, seed=31)
    idx = MemoryIndex(dim=d, capacity=n + 64, ivf_nprobe=8, pq_serving=True)
    ids = [f"m{i}" for i in range(n)]
    idx.add(ids, emb, [0.5] * n, [0.0] * n, ["semantic"] * n,
            ["default"] * n, "u0")
    assert idx.ivf_maintenance()
    occ0 = int(np.asarray(idx._ivf_dev[2]).sum())
    idx.delete(ids[: n // 2])              # half the pool becomes holes
    assert idx.ivf_member_repack(hole_frac=0.25)
    assert int(np.asarray(idx._ivf_dev[2]).sum()) < occ0
    snap = idx.telemetry.snapshot()
    assert any(k.startswith("ivf.member_repacks")
               for k in snap["counters"])
    assert any(k.startswith("ivf.member_holes_reclaimed")
               for k in snap["counters"])
    # no live row lost, no dead row surfaced
    live = set(ids[n // 2:])
    members = np.asarray(idx._ivf_dev[1])
    counts = np.asarray(idx._ivf_dev[2])
    for c in range(members.shape[0]):
        for s in range(int(counts[c])):
            assert idx.row_to_id[int(members[c, s])] in live
    res = idx.search_fused_requests(
        [RetrievalRequest(query=emb[n - 1], tenant="u0", k=3)], **KW)
    assert res[0].ids[0] == f"m{n - 1}"
    # below the hole threshold: a second call is a no-op
    assert not idx.ivf_member_repack(hole_frac=0.25)
