"""Fault-injection recovery matrix (ISSUE 10).

Every named injection point — failed donated dispatch, worker-thread
death, pump crash mid-chunk, torn checkpoint write, cold-store read
error — is driven deterministically through ``reliability.faults`` and
must end in STATE PARITY with an uninjected run: same results, bit-equal
arena/edge columns (and int8 shadow where maintained), zero hung
futures, zero lost journaled facts. The dispatch-level cells run across
{exact, quant, ivf, tiered, 2-way mesh}; actor-level cells (scheduler,
ingest worker, pump, checkpoint, cold store) run on the modes they
apply to. A jit-counter test pins that the fault-FREE path still costs
exactly ONE dispatch per serve — the guards add retries, never
dispatches.
"""

import os
import threading
import time

import numpy as np
import pytest

import jax

from lazzaro_tpu.core import checkpoint as C
from lazzaro_tpu.core import state as S
from lazzaro_tpu.core.index import MemoryIndex
from lazzaro_tpu.reliability import (ArenaPoisoned, CheckpointCorrupt,
                                     CircuitBreaker, ColdReadError,
                                     DeviceOom, DispatchTimeout,
                                     IngestJournal, LoadShed,
                                     WorkerCrashed)
from lazzaro_tpu.reliability.faults import (INJECTOR, InjectedFault,
                                            oom_error, poison_states_hook,
                                            torn_write_hook)
from lazzaro_tpu.serve.scheduler import (QueryScheduler, RetrievalRequest,
                                         RetrievalResult)
from lazzaro_tpu.utils.telemetry import Telemetry

D = 32
EPOCH = 1000.0          # shared by every index so parity covers timestamps
KW = dict(cap_take=5, max_nbr=8, super_gate=0.4, acc_boost=0.05,
          nbr_boost=0.02, now=1234.5)
MODES = ["exact", "quant", "ivf", "tiered", "mesh2"]

_ARENA_COLS = ("emb", "salience", "timestamp", "last_accessed",
               "access_count", "type_id", "shard_id", "tenant_id", "alive",
               "is_super")
_EDGE_COLS = ("src", "tgt", "weight", "co", "last_updated", "alive",
              "tenant_id")


@pytest.fixture(autouse=True)
def _clean_faults():
    INJECTOR.clear()
    yield
    INJECTOR.clear()


def _vecs(n, seed):
    r = np.random.default_rng(seed)
    nz = r.standard_normal((n, D)).astype(np.float32)
    return nz / np.linalg.norm(nz, axis=1, keepdims=True)


def _fill(idx, n=200, seed=0):
    emb = _vecs(n, seed)
    ids = [f"n{i}" for i in range(n)]
    sup = [i % 29 == 0 for i in range(n)]
    idx.add(ids, emb, [0.5] * n, [0.0] * n, ["semantic"] * n,
            ["default"] * n, "u0", is_super=sup)
    # now= pinned so two builds are bit-identical regardless of the f32
    # relative-timestamp quantum the wall clock happens to land in
    idx.add_edges([(f"n{i}", f"n{i + 1}", 0.7) for i in range(n - 1)],
                  "u0", now=EPOCH)
    return emb


def _reqs(emb, nq=8, k=10, boost=True, seed=9):
    r = np.random.default_rng(seed)
    q = emb[:nq] + 0.01 * r.standard_normal((nq, D)).astype(np.float32)
    return [RetrievalRequest(query=q[i], tenant="u0", k=k,
                             gate_enabled=True, boost=boost)
            for i in range(nq)]


def _build_mode(mode, **extra):
    """One (index, emb) fixture per matrix column, deterministic and
    epoch-pinned so two builds are bit-identical. ``extra`` forwards
    ctor kwargs (the replan cells pass an HBM-planner budget)."""
    if mode == "ivf":
        n = 4500
        idx = MemoryIndex(dim=D, capacity=5000, int8_serving=True,
                          coarse_slack=5001, ivf_nprobe=4096, epoch=EPOCH,
                          telemetry=Telemetry(), **extra)
        emb = _vecs(n, 0)
        ids = [f"n{i}" for i in range(n)]
        idx.add(ids, emb, [0.5] * n, [0.0] * n, ["semantic"] * n,
                ["default"] * n, "u0")
        idx.add_edges([(f"n{j}", f"n{j + 1}", 0.7) for j in range(200)],
                      "u0", now=EPOCH)
        assert idx.ivf_maintenance(iters=2)
        return idx, emb
    mesh = None
    if mode == "mesh2":
        from lazzaro_tpu.parallel.mesh import make_mesh
        mesh = make_mesh(("data",), (2,), devices=jax.devices()[:2])
    idx = MemoryIndex(dim=D, capacity=255, epoch=EPOCH, mesh=mesh,
                      int8_serving=(mode in ("quant", "tiered", "mesh2")),
                      coarse_slack=(8 if mode == "exact" else 512),
                      telemetry=Telemetry(), **extra)
    emb = _fill(idx)
    if mode == "tiered":
        tm = idx.enable_tiering(hot_budget_rows=64, hysteresis_s=0.0)
        tm.demote_rows([idx.id_to_row[f"n{i}"] for i in range(100, 200)])
        assert tm.cold_count > 90
    return idx, emb


def _assert_results_equal(a_list, b_list):
    for a, b in zip(a_list, b_list):
        assert a.ids == b.ids
        assert np.allclose(a.scores, b.scores, atol=2e-6)
        assert a.fast == b.fast
        assert a.gate_id == b.gate_id


def _assert_state_parity(ia, ib):
    """Bit-parity of every arena/edge column (+ int8 shadow when both
    sides maintain one) — the matrix's recovery contract."""
    for col in _ARENA_COLS:
        a = np.asarray(getattr(ia.state, col))
        b = np.asarray(getattr(ib.state, col))
        assert np.array_equal(a, b), f"arena.{col} diverged"
    for col in _EDGE_COLS:
        a = np.asarray(getattr(ia.edge_state, col))
        b = np.asarray(getattr(ib.edge_state, col))
        assert np.array_equal(a, b), f"edges.{col} diverged"
    sa, sb = ia._int8_shadow, ib._int8_shadow
    if (sa is not None and sb is not None
            and not ia._int8_dirty and not ib._int8_dirty):
        assert np.array_equal(np.asarray(sa[0]), np.asarray(sb[0]))
        assert np.array_equal(np.asarray(sa[1]), np.asarray(sb[1]))


# =====================================================================
# dispatch faults: transient raise → copy-twin retry → parity
# =====================================================================
@pytest.mark.parametrize("mode", MODES)
def test_dispatch_raise_recovers_to_parity(mode):
    """A donated serving dispatch that fails WITHOUT consuming its input
    retries through the non-donating twin: the caller sees a normal
    result, the retry is counted, and the post-recovery state is
    bit-identical to a fault-free run."""
    idx_f, emb = _build_mode(mode)
    idx_c, _ = _build_mode(mode)
    INJECTOR.arm("index.dispatch", times=1)
    r_f = idx_f.search_fused_requests(_reqs(emb), **KW)
    r_c = idx_c.search_fused_requests(_reqs(emb), **KW)
    assert INJECTOR.fired("index.dispatch") == 1
    assert idx_f.telemetry.counter_total("serve.dispatch_retries") >= 1
    _assert_results_equal(r_f, r_c)
    _assert_state_parity(idx_f, idx_c)


def test_dispatch_raise_on_ingest_recovers_to_parity():
    """The fused ingest dispatch under the same guard: one injected
    failure, transparent copy-twin retry, node/edge/shadow parity."""
    idx_f, _ = _build_mode("quant")
    idx_c, _ = _build_mode("quant")
    new = _vecs(8, 7)
    args = (["m%d" % i for i in range(8)], new, [0.5] * 8, [0.0] * 8,
            ["semantic"] * 8, ["default"] * 8, "u0")
    INJECTOR.arm("index.dispatch", times=1)
    idx_f.ingest_batch(*args, chain_pairs=[("m0", "m1")], now=1200.0)
    idx_c.ingest_batch(*args, chain_pairs=[("m0", "m1")], now=1200.0)
    assert INJECTOR.fired("index.dispatch") == 1
    assert idx_f.telemetry.counter_total("serve.dispatch_retries") >= 1
    _assert_state_parity(idx_f, idx_c)


def test_mutation_dispatch_raise_recovers():
    idx_f, _ = _build_mode("exact")
    idx_c, _ = _build_mode("exact")
    INJECTOR.arm("index.dispatch", times=1)
    idx_f.update_access(["n0", "n3"], now=2000.0)
    idx_c.update_access(["n0", "n3"], now=2000.0)
    _assert_state_parity(idx_f, idx_c)


# =====================================================================
# typed OOM (ISSUE 11): non-transient classification + replan recovery
# =====================================================================
def test_oom_dispatch_not_retried_as_transient():
    """REPRO (ISSUE 11 satellite): the guard used to retry
    RESOURCE_EXHAUSTED with backoff as if transient — re-failing
    identically until the retry budget burned. It now reclassifies the
    FIRST allocation failure into the typed DeviceOom (routing it to the
    planner), so the armed fault fires exactly once and no copy-twin
    retry ever launches."""
    idx, emb = _build_mode("exact")
    INJECTOR.arm("index.dispatch", times=3, exc=oom_error)
    with pytest.raises(DeviceOom):
        idx.update_access(["n0"], now=2000.0)
    assert INJECTOR.fired("index.dispatch") == 1   # ONE attempt, no burn
    assert idx.telemetry.counter_total("serve.dispatch_retries") == 0
    assert idx.telemetry.counter_total("reliability.oom") == 1


@pytest.mark.parametrize("mode", MODES)
def test_plan_oom_replan_recovers_to_parity(mode):
    """The replan-recovery matrix cells (ISSUE 11): an injected
    RESOURCE_EXHAUSTED at the fused dispatch on a planner-active index
    recovers by ONE replan into split sub-dispatches through the copy
    twins — results and state bit-identical to an uninjected unsplit
    run, across every serving mode."""
    idx_f, emb = _build_mode(mode, hbm_budget_bytes=1 << 34)
    idx_c, _ = _build_mode(mode)
    INJECTOR.arm("plan.oom", times=1, exc=oom_error)
    r_f = idx_f.search_fused_requests(_reqs(emb, boost=False), **KW)
    r_c = idx_c.search_fused_requests(_reqs(emb, boost=False), **KW)
    assert INJECTOR.fired("plan.oom") == 1
    assert idx_f.telemetry.counter_total("plan.oom_replans") == 1
    assert idx_f.telemetry.counter_total("plan.split_dispatches") >= 2
    _assert_results_equal(r_f, r_c)
    _assert_state_parity(idx_f, idx_c)


def test_plan_oom_without_planner_stays_typed():
    """With no planner budget configured there is nothing to replan
    with: the reclassified DeviceOom surfaces typed (never a backoff
    retry loop, never a hang)."""
    idx, emb = _build_mode("exact")
    INJECTOR.arm("plan.oom", times=1, exc=oom_error)
    with pytest.raises(DeviceOom):
        idx.search_fused_requests(_reqs(emb, boost=False), **KW)
    r = idx.search_fused_requests(_reqs(emb, boost=False), **KW)
    assert all(x.ids for x in r)                   # next serve is clean


# =====================================================================
# dispatch faults: poisoned arena → typed error, checkpoint recovery
# =====================================================================
def test_poisoned_arena_raises_typed_and_fast():
    """A donated dispatch that CONSUMED its input before failing leaves
    nothing to retry with: the index raises the typed ArenaPoisoned —
    immediately on the failing call and on every later touch — instead
    of surfacing XLA's 'Array has been deleted' from a random depth."""
    idx, emb = _build_mode("exact")
    INJECTOR.arm("index.dispatch", times=1, hook=poison_states_hook)
    with pytest.raises(ArenaPoisoned):
        idx.update_access(["n0"], now=2000.0)
    assert idx.poisoned
    with pytest.raises(ArenaPoisoned):
        idx.update_access(["n1"], now=2001.0)
    with pytest.raises(ArenaPoisoned):
        idx.search_fused_requests(_reqs(emb, nq=2), **KW)
    assert idx.telemetry.counter_total("reliability.poisoned") == 1


def test_poisoned_arena_recovers_via_checkpoint(tmp_path):
    """The poisoned-arena recovery path: restore the last checkpoint →
    bit-parity with a never-poisoned twin, serving works."""
    idx, emb = _build_mode("quant")
    ck = str(tmp_path / "ck")
    C.save_index(idx, ck)
    INJECTOR.arm("index.dispatch", times=1, hook=poison_states_hook)
    with pytest.raises(ArenaPoisoned):
        idx.update_access(["n0"], now=2000.0)
    restored = C.load_index(ck, int8_serving=True, coarse_slack=512)
    control, _ = _build_mode("quant")
    _assert_state_parity(restored, control)
    r_r = restored.search_fused_requests(_reqs(emb), **KW)
    r_c = control.search_fused_requests(_reqs(emb), **KW)
    _assert_results_equal(r_r, r_c)


# =====================================================================
# scheduler worker death: typed futures, restart, parity
# =====================================================================
@pytest.mark.parametrize("mode", MODES)
def test_worker_death_fails_futures_and_restarts(mode):
    """Pre-ISSUE-10, a worker-thread exception outside the demuxed
    executor stranded every pending future FOREVER. Now the admitted
    batch fails with the typed WorkerCrashed, the worker restarts, and
    the next submit serves normally — state parity with a run that only
    saw the successful batch (the dead batch never touched the device)."""
    idx_f, emb = _build_mode(mode)
    idx_c, _ = _build_mode(mode)
    tel = Telemetry()
    sched = QueryScheduler(
        lambda rs: idx_f.search_fused_requests(rs, **KW), telemetry=tel)
    INJECTOR.arm("scheduler.worker", times=1)
    futs = sched.submit_many(_reqs(emb, nq=4))
    for f in futs:
        with pytest.raises(WorkerCrashed):
            f.result(timeout=30)            # typed, never a hang
    futs2 = sched.submit_many(_reqs(emb, nq=4))
    res_f = [f.result(timeout=30) for f in futs2]
    sched.close()
    assert tel.counter_total("reliability.worker_restarts") >= 1
    res_c = idx_c.search_fused_requests(_reqs(emb, nq=4), **KW)
    _assert_results_equal(res_f, res_c)
    _assert_state_parity(idx_f, idx_c)


def test_executor_exception_still_demuxes_typed():
    """The PR 2 contract preserved: an executor exception resolves every
    future of that batch with the error itself."""
    def boom(reqs):
        raise ValueError("executor exploded")

    sched = QueryScheduler(boom, telemetry=Telemetry())
    f = sched.submit(RetrievalRequest(query=np.zeros(D, np.float32),
                                      tenant="t"))
    with pytest.raises(ValueError):
        f.result(timeout=30)
    sched.close()


# =====================================================================
# watchdog deadline, circuit breaker, load shedding
# =====================================================================
def _req():
    return RetrievalRequest(query=np.zeros(D, np.float32), tenant="t")


def test_watchdog_deadline_fails_futures_typed():
    def slow(reqs):
        time.sleep(0.3)
        return [RetrievalResult() for _ in reqs]

    tel = Telemetry()
    sched = QueryScheduler(slow, telemetry=tel, dispatch_timeout_s=0.05)
    f = sched.submit(_req())
    with pytest.raises(DispatchTimeout):
        f.result(timeout=30)
    sched.close()
    assert tel.counter_total("reliability.watchdog_timeouts") == 1
    assert sched.breaker.stats()["consecutive_failures"] >= 0


def test_breaker_opens_degrades_then_recovers():
    seen = []
    fail = {"n": 2}

    def ex(reqs):
        seen.append([(r.cap_take, r.nprobe) for r in reqs])
        if fail["n"] > 0:
            fail["n"] -= 1
            raise RuntimeError("device unhappy")
        return [RetrievalResult() for _ in reqs]

    tel = Telemetry()
    sched = QueryScheduler(ex, telemetry=tel, breaker_threshold=2,
                           breaker_cooldown_s=30.0)
    for _ in range(2):
        with pytest.raises(RuntimeError):
            sched.submit(_req()).result(timeout=30)
    assert sched.breaker.state == "open"
    sched.submit(_req()).result(timeout=30)       # served DEGRADED
    assert seen[-1] == [(1, 1)]                   # nprobe/cap_take clamped
    assert tel.counter_total("reliability.degraded_requests") == 1
    # cooldown elapses → half-open probe at full quality → re-close
    sched.breaker._opened_at -= 60.0
    sched.submit(_req()).result(timeout=30)
    assert seen[-1] == [(None, None)]             # full quality again
    assert sched.breaker.state == "closed"
    sched.close()


def test_load_shed_typed_and_bounded():
    gate = threading.Event()

    def ex(reqs):
        gate.wait(10)
        return [RetrievalResult() for _ in reqs]

    tel = Telemetry()
    sched = QueryScheduler(ex, telemetry=tel, shed_depth=2)
    f1 = sched.submit(_req())         # admitted by the worker, blocks
    for _ in range(200):
        with sched._cond:
            if sched._inflight == 1 and not sched._pending:
                break
        time.sleep(0.005)
    f23 = sched.submit_many([_req(), _req()])     # queue == depth: admitted
    f4 = sched.submit(_req())                     # over budget: shed
    with pytest.raises(LoadShed):
        f4.result(timeout=30)
    gate.set()
    assert isinstance(f1.result(timeout=30), RetrievalResult)
    for f in f23:
        assert isinstance(f.result(timeout=30), RetrievalResult)
    sched.close()
    assert tel.counter_total("reliability.load_shed") == 1
    assert sched.requests_shed == 1


def test_breaker_unit_transitions():
    br = CircuitBreaker(threshold=2, cooldown_s=0.01)
    assert br.state == "closed" and not br.degraded(now=0.0)
    br.record_failure(now=0.0)
    assert br.state == "closed"
    br.record_failure(now=0.0)
    assert br.state == "open" and br.opens == 1
    assert br.degraded(now=0.005)                 # inside cooldown
    assert not br.degraded(now=0.02)              # → half-open probe
    assert br.state == "half_open"
    br.record_failure(now=0.03)                   # probe failed → re-open
    assert br.state == "open" and br.opens == 2
    assert not br.degraded(now=1.0)
    br.record_success()
    assert br.state == "closed"


# =====================================================================
# durable ingest journal
# =====================================================================
def test_ingest_journal_append_commit_replay(tmp_path):
    p = str(tmp_path / "ing.wal")
    j = IngestJournal(p)
    s1 = j.append([{"content": "a"}])
    s2 = j.append([{"content": "b"}, {"content": "c"}])
    assert (s1, s2) == (1, 2)
    j2 = IngestJournal(p)                         # crash + reopen
    assert [s for s, _ in j2.pending()] == [1, 2]
    j2.commit(s1)
    j3 = IngestJournal(p)
    assert [f for _, f in j3.pending()] == [[{"content": "b"},
                                             {"content": "c"}]]
    j3.commit(j3.last_seq)                        # retires all → compacts
    assert os.path.getsize(p) == 0
    # sequence numbers keep advancing after compaction
    j4 = IngestJournal(p)
    s3 = j4.append([{"content": "d"}])
    with open(p, "ab") as f:
        f.write(b"\x31WZL\x99garbage")            # torn tail record
    j5 = IngestJournal(p)
    assert [s for s, _ in j5.pending()] == [s3]


def _system_ms(tmp, llm=None):
    from lazzaro_tpu.config import MemoryConfig
    from lazzaro_tpu.core.memory_system import MemorySystem
    from tests.test_fused_ingest import ClusteredEmb, QueueLLM

    return MemorySystem(
        enable_async=False, db_dir=tmp, verbose=False, load_from_disk=False,
        llm_provider=llm or QueueLLM(4), embedding_provider=ClusteredEmb(),
        auto_prune=False, max_buffer_size=10_000,
        config=MemoryConfig(journal=True, auto_consolidate=False,
                            decay_rate=0.0))


def _count_facts(ms, content):
    return sum(1 for shard in ms.shards.values()
               for n in shard.nodes.values() if n.content == content)


def test_ingest_worker_death_zero_lost_facts(tmp_db):
    """Worker dies between extraction and ingest: the facts are already
    journaled, so a 'crashed' process replays them on startup through
    the normal ingest path — zero lost facts."""
    from lazzaro_tpu.config import MemoryConfig
    from lazzaro_tpu.core.memory_system import MemorySystem
    from tests.test_fused_ingest import ClusteredEmb, QueueLLM

    ms = _system_ms(tmp_db)
    ms.start_conversation()
    ms.add_to_short_term("turn one", "semantic", 0.6)
    INJECTOR.arm("ingest.worker", times=1)
    ms.end_conversation()                 # extraction ok, worker "dies"
    assert INJECTOR.fired("ingest.worker") == 1
    assert ms._ingest_journal.pending_count == 1
    assert _count_facts(ms, "fact 0 body") == 0   # nothing ingested yet
    # simulated crash: no close(). A fresh process on the same db_dir:
    ms2 = MemorySystem(
        enable_async=False, db_dir=tmp_db, load_from_disk=True,
        verbose=False, llm_provider=QueueLLM(4),
        embedding_provider=ClusteredEmb(),
        config=MemoryConfig(journal=True, auto_consolidate=False,
                            decay_rate=0.0))
    assert ms2._ingest_journal.pending_count == 0     # replayed + committed
    assert ms2.telemetry.counter_total("reliability.journal_replayed") == 4
    assert _count_facts(ms2, "fact 0 body") == 1
    ms2.close()


def test_journal_replay_is_idempotent(tmp_db):
    """Crash AFTER the dispatch but BEFORE the commit: replay re-ingests
    facts that already landed — the in-dispatch dedup probe collapses
    them into merges, so the corpus holds each fact exactly once."""
    from lazzaro_tpu.config import MemoryConfig
    from lazzaro_tpu.core.memory_system import MemorySystem
    from tests.test_fused_ingest import ClusteredEmb, QueueLLM

    ms = _system_ms(tmp_db)
    ms.start_conversation()
    ms.add_to_short_term("turn one", "semantic", 0.6)
    ms.end_conversation()                 # clean ingest, journal committed
    assert _count_facts(ms, "fact 0 body") == 1
    # re-append the same facts = "crashed before commit"
    facts = [{"content": f"fact {i} body", "type": "semantic",
              "salience": 0.6, "topic": "work"} for i in range(4)]
    ms._ingest_journal.append(facts)
    ms._save_to_persistence()
    ms2 = MemorySystem(
        enable_async=False, db_dir=tmp_db, load_from_disk=True,
        verbose=False, llm_provider=QueueLLM(4),
        embedding_provider=ClusteredEmb(),
        config=MemoryConfig(journal=True, auto_consolidate=False,
                            decay_rate=0.0))
    assert ms2._ingest_journal.pending_count == 0
    assert _count_facts(ms2, "fact 0 body") == 1      # merged, not doubled
    ms2.close()


def test_ingest_dispatch_failure_requeues_and_retries(tmp_db):
    """The fused ingest dispatch fails past its retry budget: the facts
    go back to the coalescer front + stay journaled, the worker survives,
    and the next consolidation lands them exactly once."""
    ms = _system_ms(tmp_db)
    ms.start_conversation()
    ms.add_to_short_term("turn one", "semantic", 0.6)
    # 1 initial attempt + dispatch_retry_max(2) retries = 3 fires exhausts
    # the guard for the ONE ingest dispatch; decay afterwards runs clean.
    INJECTOR.arm("index.dispatch", times=3)
    ms.end_conversation()
    assert len(ms._ingest_coalescer) == 4         # facts requeued
    assert ms._ingest_journal.pending_count == 1
    assert ms.telemetry.counter_total("reliability.ingest_failures") == 1
    INJECTOR.clear()
    ms.start_conversation()
    ms.add_to_short_term("turn two", "semantic", 0.6)
    ms.end_conversation()                 # drains requeued + new facts
    assert _count_facts(ms, "fact 0 body") == 1
    assert _count_facts(ms, "fact 4 body") == 1   # second extraction's
    assert ms._ingest_journal.pending_count == 0  # all committed
    ms.close()


# =====================================================================
# tier pump: commit-then-zero, crash mid-chunk, cold-store read errors
# =====================================================================
def test_pump_mid_chunk_crash_leaves_rows_hot(tmp_path):
    """The pump dies between the cold-store commit and the hot
    zero-scatter: commit-then-zero means the master row was NOT zeroed —
    the rows stay hot, the cold residue is dropped, and the next pass
    demotes cleanly."""
    idx_f, emb = _build_mode("quant")
    idx_c, _ = _build_mode("quant")
    tm = idx_f.enable_tiering(hot_budget_rows=64, hysteresis_s=0.0,
                              cold_dir=str(tmp_path / "cold"))
    # n116 / n145 are super rows (pinned hot): 48 of the 50 are demotable
    rows = [idx_f.id_to_row[f"n{i}"] for i in range(100, 150)]
    demotable = [r for r in rows if r not in idx_f._super_rows]
    INJECTOR.arm("pump.mid_chunk", times=1)
    with pytest.raises(InjectedFault):
        tm.demote_rows(rows)
    assert tm.cold_count == 0 and not tm.cold_np.any()
    _assert_state_parity(idx_f, idx_c)            # master untouched
    assert tm.demote_rows(rows) == len(demotable)  # clean retry next pass
    assert tm.cold_count == len(demotable)
    emb_now = np.asarray(idx_f.state.emb)
    assert not emb_now[demotable].any()           # now demoted for real


def test_pump_thread_survives_injected_crash():
    from lazzaro_tpu.tier import TierPump

    idx, _ = _build_mode("quant")
    tm = idx.enable_tiering(hot_budget_rows=32, hysteresis_s=0.0)
    INJECTOR.arm("pump.mid_chunk", times=1)
    pump = TierPump(tm, interval_s=0.02).start()
    deadline = time.time() + 30
    while time.time() < deadline and tm.cold_count == 0:
        time.sleep(0.02)
    assert INJECTOR.fired("pump.mid_chunk") == 1  # the crash happened
    assert tm.cold_count > 0                      # and a later pass won
    assert pump.running                           # pump never died
    pump.stop()
    assert idx.telemetry.counter_total("reliability.worker_restarts") >= 1


def test_coldstore_read_error_typed_and_recovers():
    """An injected cold-tier read error surfaces typed from the serving
    path (read-only turn: no partial boosts), and the next serve returns
    bit-parity with an uninjected index."""
    idx_f, emb = _build_mode("tiered")
    idx_c, _ = _build_mode("tiered")
    INJECTOR.arm("coldstore.read", times=1, exc=ColdReadError)
    with pytest.raises(ColdReadError):
        idx_f.search_fused_requests(_reqs(emb, boost=False), **KW)
    r_f = idx_f.search_fused_requests(_reqs(emb, boost=False), **KW)
    r_c = idx_c.search_fused_requests(_reqs(emb, boost=False), **KW)
    _assert_results_equal(r_f, r_c)
    _assert_state_parity(idx_f, idx_c)


def test_coldstore_read_error_on_promote_recovers():
    idx, _ = _build_mode("tiered")
    tm = idx.tiering
    cold_rows = sorted(np.flatnonzero(tm.cold_np).tolist())[:8]
    INJECTOR.arm("coldstore.read", times=1, exc=ColdReadError)
    with pytest.raises(ColdReadError):
        tm.promote_rows(cold_rows)
    assert tm.cold_np[cold_rows].all()            # still cold, consistent
    assert tm.promote_rows(cold_rows) == 8        # clean retry
    assert not tm.cold_np[cold_rows].any()


# =====================================================================
# torn checkpoint
# =====================================================================
def test_torn_checkpoint_raises_typed_and_resave_recovers(tmp_path):
    """A torn checkpoint write (payload corrupted after the CURRENT
    flip) must fail its checksum with the typed CheckpointCorrupt —
    never deserialize garbage — and a re-save from the live index
    restores full parity, including the tier residency + cold payload."""
    idx, emb = _build_mode("tiered")
    ck = str(tmp_path / "ck")
    INJECTOR.arm("checkpoint.torn", times=1, exc=None,
                 hook=torn_write_hook())
    C.save_index(idx, ck)                 # "succeeds" — silently torn
    with pytest.raises(CheckpointCorrupt):
        C.load_index(ck, int8_serving=True, coarse_slack=512)
    C.save_index(idx, ck)                 # recovery: re-save, no fault
    restored = C.load_index(ck, int8_serving=True, coarse_slack=512)
    _assert_state_parity(restored, idx)
    assert restored.tiering is not None
    assert restored.tiering.cold_count == idx.tiering.cold_count
    r_r = restored.search_fused_requests(_reqs(emb, boost=False), **KW)
    r_o = idx.search_fused_requests(_reqs(emb, boost=False), **KW)
    _assert_results_equal(r_r, r_o)


def test_checkpoint_checksum_catches_bit_rot(tmp_path):
    idx, _ = _build_mode("exact")
    ck = str(tmp_path / "ck")
    C.save_index(idx, ck)
    cur = open(os.path.join(ck, "CURRENT")).read().strip()
    npz = os.path.join(ck, cur, "arrays.npz")
    with open(npz, "r+b") as f:           # flip bytes mid-file
        f.seek(os.path.getsize(npz) // 2)
        f.write(b"\xff\xff\xff\xff")
    with pytest.raises(CheckpointCorrupt):
        C.load_index(ck)


# =====================================================================
# fault-free path: the guards add ZERO dispatches
# =====================================================================
def test_fault_free_serve_still_one_dispatch(monkeypatch):
    """dispatches_per_turn == 1 is preserved with the reliability layer
    on: the guard wraps the same single donated dispatch — no probe, no
    shadow dispatch, no retry on the healthy path."""
    counted = ("search_fused_ragged", "search_fused_ragged_copy",
               "search_fused_ragged_read", "search_fused",
               "search_fused_copy", "arena_search")
    calls = {name: 0 for name in counted}
    for name in counted:
        orig = getattr(S, name)

        def wrapped(*a, __orig=orig, __name=name, **kw):
            calls[__name] += 1
            return __orig(*a, **kw)

        monkeypatch.setattr(S, name, wrapped)
    idx, emb = _build_mode("exact")
    idx.search_fused_requests(_reqs(emb, nq=4), **KW)
    assert calls["search_fused_ragged"] == 1      # ONE donated dispatch
    for name in counted:
        if name != "search_fused_ragged":
            assert calls[name] == 0, (name, calls)
    assert idx.telemetry.counter_total("serve.dispatch_retries") == 0
