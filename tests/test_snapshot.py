"""MemorySystem binary snapshots: save/load without materializing embeddings."""

import numpy as np
import pytest

from lazzaro_tpu.core.memory_system import MemorySystem


def _seeded_system(db_dir):
    ms = MemorySystem(enable_async=False, db_dir=db_dir, verbose=False,
                      load_from_disk=False)
    ms.start_conversation()
    ms.chat("I work as a data engineer on a big ETL project.")
    ms.chat("I love hiking in the mountains on weekends.")
    ms.end_conversation()
    return ms


def test_snapshot_round_trip(tmp_path):
    ms = _seeded_system(str(tmp_path / "db"))
    before = [n.content for n in ms.search_memories("what is the user's job?")]
    assert before
    snap = str(tmp_path / "snap")
    ms.save_snapshot(snap)
    ms.close()

    ms2 = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db2"),
                       verbose=False, load_from_disk=False)
    msg = ms2.load_snapshot(snap)
    assert "loaded" in msg
    after = [n.content for n in ms2.search_memories("what is the user's job?")]
    assert after == before
    assert ms2.conversation_count == ms.conversation_count
    assert ms2.node_counter == ms.node_counter
    # Host nodes restored WITHOUT embeddings (the arena owns the vectors).
    assert all(n.embedding is None for n in ms2.buffer.nodes.values())
    ms2.close()


def test_snapshot_then_persistence_keeps_embeddings(tmp_path):
    """After load_snapshot, a store save must pull embeddings from the arena
    (host copies are None) so a later store reload still retrieves."""
    ms = _seeded_system(str(tmp_path / "db"))
    snap = str(tmp_path / "snap")
    ms.save_snapshot(snap)
    ms.close()

    db2 = str(tmp_path / "db2")
    ms2 = MemorySystem(enable_async=False, db_dir=db2, verbose=False,
                       load_from_disk=False)
    ms2.load_snapshot(snap)
    ms2._save_to_persistence()
    rows = ms2.store.get_nodes(user_id=ms2.user_id)
    assert rows and all(len(r["embedding"]) == ms2.embed_dim for r in rows)
    ms2.close()

    ms3 = MemorySystem(enable_async=False, db_dir=db2, verbose=False,
                       load_from_disk=True)
    hits = [n.content for n in ms3.search_memories("hiking mountains")]
    assert any("hiking" in h for h in hits)
    ms3.close()


def test_snapshot_system_remains_usable(tmp_path):
    """The restored system keeps ingesting: new conversation, dedup-merge
    against snapshot-loaded nodes, consolidation."""
    ms = _seeded_system(str(tmp_path / "db"))
    snap = str(tmp_path / "snap")
    ms.save_snapshot(snap)
    n_before = len(ms.buffer.nodes)
    ms.close()

    ms2 = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db2"),
                       verbose=False, load_from_disk=False)
    ms2.load_snapshot(snap)
    ms2.start_conversation()
    # Same fact → dedup-merge into the snapshot-loaded node: still exactly
    # one node holding it (assistant-response facts may add other nodes).
    ms2.chat("I work as a data engineer on a big ETL project.")
    ms2.end_conversation()
    fact = "I work as a data engineer on a big ETL project"
    engineer_nodes = [n for n in ms2.buffer.nodes.values()
                      if n.content == fact]
    assert len(engineer_nodes) == 1
    assert engineer_nodes[0].access_count >= 1      # merge touched it
    assert len(ms2.buffer.nodes) >= n_before
    ms2.run_consolidation()
    ms2.close()


def test_snapshot_preserves_other_tenants_in_index(tmp_path):
    ms = _seeded_system(str(tmp_path / "db"))
    ms.switch_user("alice")
    ms.start_conversation()
    ms.chat("I am a violinist in an orchestra.")
    ms.end_conversation()
    snap = str(tmp_path / "snap")
    ms.save_snapshot(snap)          # taken as alice
    ms.close()

    ms2 = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db2"),
                       verbose=False, load_from_disk=False)
    ms2.load_snapshot(snap)
    assert ms2.user_id == "alice"   # snapshot restores its user context
    hits = [n.content for n in ms2.search_memories("violin")]
    assert any("violinist" in h for h in hits)
    # default tenant's rows survived in the arena (index-level check).
    assert ms2.index.tenant_nodes.get("default")
    ms2.close()


def test_restore_then_save_state_keeps_embeddings(tmp_path):
    """/restore → /save (JSON) → /load must stay searchable: save_state
    fills unmaterialized embeddings from the arena."""
    ms = _seeded_system(str(tmp_path / "db"))
    snap = str(tmp_path / "snap")
    ms.save_snapshot(snap)
    ms.close()

    ms2 = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db2"),
                       verbose=False, load_from_disk=False)
    ms2.load_snapshot(snap)
    state_file = str(tmp_path / "state.json")
    ms2.save_state(state_file)
    ms2.close()

    ms3 = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db3"),
                       verbose=False, load_from_disk=False)
    ms3.load_state(state_file)
    hits = [n.content for n in ms3.search_memories("hiking mountains")]
    assert any("hiking" in h for h in hits)
    ms3.close()


def test_async_snapshot_drains_consolidation(tmp_path):
    """enable_async=True: a snapshot right after end_conversation must
    include the just-queued consolidation (drain barrier, no deadlock)."""
    ms = MemorySystem(enable_async=True, db_dir=str(tmp_path / "db"),
                      verbose=False, load_from_disk=False)
    ms.start_conversation()
    ms.chat("My cat is named Whiskers and loves tuna.")
    ms.end_conversation()                  # queues background consolidation
    snap = str(tmp_path / "snap")
    ms.save_snapshot(snap)                 # must drain first
    ms.close()

    ms2 = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db2"),
                       verbose=False, load_from_disk=False)
    ms2.load_snapshot(snap)
    hits = [n.content for n in ms2.search_memories("cat named Whiskers")]
    assert any("Whiskers" in h for h in hits)
    ms2.close()


def test_restore_discards_inflight_conversation(tmp_path):
    """/restore mid-conversation must not leak pre-restore turns into the
    restored graph."""
    ms = _seeded_system(str(tmp_path / "db"))
    snap = str(tmp_path / "snap")
    ms.save_snapshot(snap)

    ms.start_conversation()
    ms.chat("This turn must NOT survive the restore.")
    assert ms.conversation_active and ms.short_term_memory
    ms.load_snapshot(snap)
    assert not ms.conversation_active
    assert not ms.short_term_memory and not ms.conversation_history
    # The discarded turn never consolidates into the restored graph.
    ms.start_conversation()
    ms.end_conversation()
    assert not any("must NOT survive" in n.content
                   for n in ms.buffer.nodes.values())
    ms.close()


def test_restore_reopens_journal_for_snapshot_user(tmp_path):
    ms = _seeded_system(str(tmp_path / "db"))
    ms.switch_user("alice")
    ms.start_conversation()
    ms.chat("I play the violin.")
    ms.end_conversation()
    snap = str(tmp_path / "snap")
    ms.save_snapshot(snap)
    ms.close()

    ms2 = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db2"),
                       verbose=False, load_from_disk=False)
    ms2.load_snapshot(snap)
    assert ms2.user_id == "alice"
    if ms2._journal is not None:           # journal active with a real store
        assert "alice" in ms2._journal.path
        # New turns journal under alice, not the pre-restore default user.
        ms2.start_conversation()
        ms2.chat("Practicing scales today.")
        assert (tmp_path / "db2" / "journal__alice.wal").exists()
    ms2.close()


def test_corrupt_snapshot_leaves_system_intact(tmp_path):
    ms = _seeded_system(str(tmp_path / "db"))
    before = [n.content for n in ms.search_memories("data engineer work")]

    # host.json present but no index checkpoint underneath.
    bad = tmp_path / "bad_snap"
    bad.mkdir()
    (bad / "host.json").write_text('{"user_id": "default", "shards": {}}')
    msg = ms.load_snapshot(str(bad))
    assert msg.startswith("⚠")
    # Old graph untouched — staging failed before any mutation.
    after = [n.content for n in ms.search_memories("data engineer work")]
    assert after == before
    ms.close()


def test_load_snapshot_missing_dir(tmp_path):
    ms = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db"),
                      verbose=False, load_from_disk=False)
    assert "No snapshot" in ms.load_snapshot(str(tmp_path / "nope"))
    ms.close()


def test_snapshot_pair_mismatch_warns(tmp_path):
    # host.json and the index checkpoint are written separately; a crash
    # between the writes pairs a fresh half with a stale one. Both halves
    # carry the save's snapshot_id, and load warns when they disagree.
    import json, os
    ms = _seeded_system(str(tmp_path / "db"))
    snap = str(tmp_path / "snap")
    ms.save_snapshot(snap)
    ms.close()

    hj = os.path.join(snap, "host.json")
    host = json.load(open(hj))
    assert host["snapshot_id"]
    host["snapshot_id"] = "deadbeef" * 4       # simulate a stale half
    json.dump(host, open(hj, "w"))

    ms2 = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db2"),
                       verbose=False, load_from_disk=False)
    msg = ms2.load_snapshot(snap)
    assert "loaded" in msg and "different snapshot ids" in msg
    ms2.close()


def test_restore_preserves_ivf_serving_config(tmp_path):
    """config.ivf_serving must survive load_snapshot the way int8_serving
    does — a restored system silently serving exact forever (and never
    running the worker's ivf_maintenance hook) was advisor r4's medium
    finding."""
    from lazzaro_tpu.config import MemoryConfig

    ms = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db"),
                      verbose=False, load_from_disk=False,
                      config=MemoryConfig(journal=False, int8_serving=True,
                                          ivf_serving=6))
    ms.start_conversation()
    ms.chat("I work as a data engineer on a big ETL project.")
    ms.end_conversation()
    snap = str(tmp_path / "snap")
    ms.save_snapshot(snap)
    ms.close()

    ms2 = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db2"),
                       verbose=False, load_from_disk=False,
                       config=MemoryConfig(journal=False, int8_serving=True,
                                           ivf_serving=6))
    assert "loaded" in ms2.load_snapshot(snap)
    assert ms2.index.ivf_nprobe == 6
    assert ms2.index.int8_serving
    ms2.close()
