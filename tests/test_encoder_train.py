"""Encoder fine-tuning: InfoNCE train step (models/encoder.py:265-302).

Covers the "fine-tune the retrieval encoder on your own memory corpus"
capability (a thing the reference cannot do — its embedders are remote
APIs, providers.py:36-57): loss decreases on a tiny synthetic corpus, the
step runs data-parallel over a mesh 'data' axis, and the fine-tuned
encoder drives the semantic thresholds through ``EncoderEmbedder`` —
exercising dedup/link gates on REAL encoder geometry instead of hash
vectors (verdict r2 weak #7).
"""

# Compile-heavy (multi-second XLA compiles / 100k-row arenas): the
# default lane must stay inside a driver window; run the full lane
# with no -m filter for round gates.
pytestmark = __import__("pytest").mark.slow

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from lazzaro_tpu.models.encoder import (EncoderConfig, TextEncoder,
                                        make_encoder_train_step)

CFG = EncoderConfig.tiny()

# (query, positive) pairs: four topic clusters, paraphrase positives.
PAIRS = [
    ("the cat sat on the mat", "a cat resting on a mat"),
    ("stock markets fell sharply today", "equities dropped steeply this session"),
    ("how to bake sourdough bread", "baking bread with a sourdough starter"),
    ("football match ended in a draw", "the soccer game finished level"),
    ("rain is expected this weekend", "weekend forecast calls for showers"),
    ("new laptop battery lasts all day", "the notebook runs a full day per charge"),
    ("she plays violin in an orchestra", "an orchestral violinist"),
    ("recipe for spicy lentil soup", "cooking a hot lentil soup"),
]


def _tokenize(enc, texts):
    return jnp.asarray(enc.tokenizer.batch_encode(list(texts), CFG.max_len),
                       jnp.int32)


def _train(mesh=None, steps=25):
    enc = TextEncoder(CFG, seed=0)
    opt = optax.adam(3e-4)
    step = make_encoder_train_step(CFG, opt, mesh=mesh)
    params = enc.params
    opt_state = opt.init(params)
    q_ids = _tokenize(enc, [q for q, _ in PAIRS])
    p_ids = _tokenize(enc, [p for _, p in PAIRS])
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state, q_ids, p_ids)
        losses.append(float(loss))
    enc.params = params
    return enc, losses


def test_loss_decreases():
    _, losses = _train()
    assert losses[-1] < losses[0] * 0.5, losses[:3] + losses[-3:]
    assert np.isfinite(losses).all()


def test_train_step_runs_under_data_mesh():
    from lazzaro_tpu.parallel.mesh import make_mesh

    n = len(jax.devices())
    if n < 2:
        pytest.skip("needs the multi-device CPU mesh from conftest")
    mesh = make_mesh(("data",), (n,))
    _, losses = _train(mesh=mesh, steps=10)
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_finetuned_encoder_drives_thresholds():
    """After fine-tuning, paraphrase pairs score above the link gate (0.5)
    and above unrelated pairs — the geometry the dedup/link thresholds
    assume, produced by a REAL encoder forward instead of hash features."""
    from lazzaro_tpu.core.providers import EncoderEmbedder

    enc, _ = _train(steps=60)
    emb = EncoderEmbedder(enc)
    assert emb.dim == enc.dim

    qs = np.asarray(emb.batch_embed([q for q, _ in PAIRS]), np.float32)
    ps = np.asarray(emb.batch_embed([p for _, p in PAIRS]), np.float32)
    sims = qs @ ps.T
    diag = np.diag(sims)
    off = sims[~np.eye(len(PAIRS), dtype=bool)]
    # paraphrases separate from unrelated texts, and margins are healthy
    assert diag.mean() > off.mean() + 0.2
    assert (diag > off.max(axis=0)).mean() >= 0.75

    # the trained embedder drives the ingest pipeline end-to-end: a
    # paraphrase stored earlier is retrieved for its query formulation
    from lazzaro_tpu.core.memory_system import MemorySystem
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        ms = MemorySystem(enable_async=False, db_dir=td + "/db", verbose=False,
                          load_from_disk=False, embedding_provider=emb)
        ms.start_conversation()
        ms.add_to_short_term("a cat resting on a mat", "semantic", 0.8)
        ms.end_conversation()
        hits = ms.search_memories("the cat sat on the mat")
        assert hits, "fine-tuned encoder retrieved nothing for a paraphrase"
        ms.close()
