"""Decoder LM: KV-cache consistency, training convergence, sharded step,
ring attention correctness, OnDeviceLLM provider plumbing."""

# Compile-heavy (multi-second XLA compiles / 100k-row arenas): the
# default lane must stay inside a driver window; run the full lane
# with no -m filter for round gates.
pytestmark = __import__("pytest").mark.slow

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from lazzaro_tpu.models.llm import (Decoder, LMConfig, LanguageModel,
                                    make_train_step, shard_params)
from lazzaro_tpu.models.tokenizer import ByteTokenizer
from lazzaro_tpu.parallel.mesh import make_mesh
from lazzaro_tpu.parallel.ring_attention import (make_ring_attention,
                                                 reference_causal_attention)


@pytest.fixture(scope="module")
def lm():
    return LanguageModel(LMConfig.tiny(), seed=0)


def test_byte_tokenizer_lossless():
    tok = ByteTokenizer()
    text = "Héllo wörld! 日本語 123"
    assert tok.decode(tok.encode(text, add_bos=True)) == text


def test_prefill_matches_full_forward(lm):
    ids = lm.tokenizer.encode("abcdefgh")
    tokens = jnp.asarray([ids], jnp.int32)
    pos = jnp.arange(len(ids))[None, :]
    full, _ = lm.model.apply({"params": lm.params}, tokens, pos)
    caches = lm._empty_cache(1)
    pre, caches = lm._prefill(lm.params, tokens, pos, caches)
    assert float(jnp.abs(full[:, -1] - pre).max()) < 1e-3


def test_cached_decode_matches_full_forward(lm):
    ids = lm.tokenizer.encode("memory systems")
    tokens = jnp.asarray([ids], jnp.int32)
    pos = jnp.arange(len(ids))[None, :]
    caches = lm._empty_cache(1)
    logits, caches = lm._prefill(lm.params, tokens, pos, caches)
    nxt = jnp.argmax(logits, -1).astype(jnp.int32)
    step_logits, _ = lm._decode_one(lm.params, nxt,
                                    jnp.asarray([len(ids)], jnp.int32), caches)
    tokens2 = jnp.concatenate([tokens, nxt[:, None]], 1)
    pos2 = jnp.arange(len(ids) + 1)[None, :]
    full2, _ = lm.model.apply({"params": lm.params}, tokens2, pos2)
    assert float(jnp.abs(full2[:, -1] - step_logits).max()) < 1e-3


def test_generate_returns_text(lm):
    out = lm.generate("hello", max_new_tokens=4, temperature=0.0)
    assert isinstance(out, str)
    out2 = lm.generate("hello", max_new_tokens=4, temperature=0.0)
    assert out == out2  # greedy decode is deterministic


def test_train_step_reduces_loss():
    cfg = LMConfig.tiny()
    model = Decoder(cfg)
    tok0 = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tok0, tok0)["params"]
    opt = optax.adamw(1e-2)
    opt_state = opt.init(params)
    step = make_train_step(cfg, opt)
    batch = jnp.asarray(np.random.RandomState(0).randint(0, 250, (4, 32)), jnp.int32)
    mask = jnp.ones_like(batch)
    first = last = None
    for i in range(8):
        params, opt_state, loss = step(params, opt_state, batch, mask)
        first = float(loss) if first is None else first
        last = float(loss)
    assert last < first


def test_sharded_train_step_dp_tp():
    mesh = make_mesh(("data", "model"), (2, 4))
    cfg = LMConfig.tiny()
    model = Decoder(cfg)
    tok0 = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tok0, tok0)["params"]
    params = shard_params(params, mesh)
    assert params["embed"].sharding.spec == P("model", None)
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)
    step = make_train_step(cfg, opt, mesh)
    batch = jax.device_put(
        jnp.asarray(np.random.RandomState(0).randint(0, 250, (8, 32)), jnp.int32),
        NamedSharding(mesh, P("data", None)))
    mask = jnp.ones_like(batch)
    params, opt_state, l1 = step(params, opt_state, batch, mask)
    params, opt_state, l2 = step(params, opt_state, batch, mask)
    assert float(l2) < float(l1)


def test_ring_attention_matches_dense():
    mesh = make_mesh(("sp",), (8,))
    B, T, H, D = 2, 64, 4, 16
    rng = np.random.RandomState(0)
    q, k, v = (rng.randn(B, T, H, D).astype(np.float32) for _ in range(3))
    sh = NamedSharding(mesh, P(None, "sp", None, None))
    ring = make_ring_attention(mesh, "sp")
    out = ring(jax.device_put(q, sh), jax.device_put(k, sh), jax.device_put(v, sh))
    ref = reference_causal_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    assert float(jnp.abs(out - ref).max()) < 1e-4


def test_on_device_llm_provider(lm):
    from lazzaro_tpu.core.providers import OnDeviceLLM
    provider = OnDeviceLLM(lm=lm, max_new_tokens=4)
    out = provider.completion([{"role": "user", "content": "hi"}])
    assert isinstance(out, str)
    chunks = list(provider.completion_stream([{"role": "user", "content": "hi"}]))
    assert "".join(chunks) == out


def test_generate_stream_matches_generate(lm):
    """Greedy streaming concatenates to exactly the non-streaming output
    (incremental UTF-8 replace == whole-sequence replace), across seeds so
    invalid multi-byte sequences from random weights get exercised."""
    for seed in range(3):
        full = lm.generate("stream parity", max_new_tokens=24, seed=seed)
        pieces = list(lm.generate_stream("stream parity", max_new_tokens=24,
                                         seed=seed))
        assert "".join(pieces) == full


def test_generate_stream_temperature_matches(lm):
    full = lm.generate("hot", max_new_tokens=16, temperature=0.9, seed=5)
    pieces = list(lm.generate_stream("hot", max_new_tokens=16,
                                     temperature=0.9, seed=5))
    assert "".join(pieces) == full


def test_generate_stream_subword_tokenizer_keeps_whitespace():
    """Subword decode merges tokens with spaces the per-token decode would
    drop; the prefix-delta stream must reproduce generate() exactly."""
    from lazzaro_tpu.models.llm import LanguageModel, LMConfig

    class SPLikeTok:
        eos_id = 1

        def encode(self, text, add_bos=True, add_eos=False):
            return [5, 6, 7]

        def decode(self, ids):
            return " ".join(f"w{i}" for i in ids)   # sentencepiece-ish join

    lm = LanguageModel(LMConfig.tiny(), seed=3, tokenizer=SPLikeTok())
    assert lm.eos_id == 1
    full = lm.generate("x", max_new_tokens=6)
    pieces = list(lm.generate_stream("x", max_new_tokens=6))
    assert "".join(pieces) == full
    if full.count("w") > 1:
        assert " " in full                           # spaces survived


def test_eos_id_zero_respected():
    from lazzaro_tpu.models.llm import LanguageModel, LMConfig

    class EosZeroTok:
        EOS = 0

        def encode(self, text, add_bos=True, add_eos=False):
            return [5, 6]

        def decode(self, ids):
            return "".join(chr(65 + i % 26) for i in ids)

    lm = LanguageModel(LMConfig.tiny(), tokenizer=EosZeroTok())
    assert lm.eos_id == 0


def test_json_stream_yields_complete_document(lm):
    import json as _json
    from lazzaro_tpu.core.providers import OnDeviceLLM
    provider = OnDeviceLLM(lm=lm, max_new_tokens=32)
    chunks = list(provider.completion_stream(
        [{"role": "user", "content": "extract"}],
        response_format={"type": "json_object"}))
    assert isinstance(_json.loads("".join(chunks)), dict)


def test_on_device_llm_drives_full_memory_pipeline(tmp_path):
    """System integration: a REAL on-TPU decoder (random weights) in the
    consolidation loop. Grammar-constrained decoding guarantees the
    extraction response parses, so the pipeline completes end-to-end —
    chat → end_conversation → consolidation → search — with an actual
    model generating, never the canned/heuristic fallback (SURVEY §7.5:
    the on-TPU LLM is IN the loop, not beside it)."""
    from lazzaro_tpu.core.memory_system import MemorySystem
    from lazzaro_tpu.core.providers import OnDeviceLLM
    from lazzaro_tpu.models.llm import LanguageModel, LMConfig

    provider = OnDeviceLLM(lm=LanguageModel(LMConfig.tiny(), seed=3),
                           max_new_tokens=48)
    ms = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db"),
                      verbose=False, load_from_disk=False,
                      llm_provider=provider)
    ms.start_conversation()
    reply = ms.chat("I work as a data engineer on a big ETL project.")
    assert isinstance(reply, str)          # model-generated (noise is fine)
    out = ms.end_conversation()            # extraction via grammar JSON
    assert "Consolidation complete" in out
    # The USER's turn is always in the graph (short-term buffer ingests it
    # even when the random-weight extractor returns an empty document).
    hits = ms.search_memories("data engineer")
    assert isinstance(hits, list)
    ms.close()
