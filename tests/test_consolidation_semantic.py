"""Full ingest path: STM → end_conversation → fact extraction → node creation;
second conversation merges a >0.95-similar fact into the same node.

Mirrors reference tests/test_consolidation_semantic.py (SURVEY §4): asserts
node count stays 1 across the duplicate and the exact decayed salience
0.2 + (0.9 - 0.2) * 0.99 = 0.893 after one decay pass."""

import pytest

from lazzaro_tpu import MemorySystem

from tests.fakes import MockEmbedder, MockLLM, extraction_response

FACT = {"content": "User loves the Python programming language",
        "type": "semantic", "salience": 0.9, "topic": "learning"}


@pytest.fixture()
def ms(tmp_db):
    llm = MockLLM(sniffers={
        "Extract distinct, atomic facts": extraction_response([FACT]),
    }, response="chat reply")
    system = MemorySystem(
        enable_async=False,       # force synchronous consolidation (SURVEY §4(c))
        auto_consolidate=False,
        load_from_disk=False,
        db_dir=tmp_db,
        llm_provider=llm,
        embedding_provider=MockEmbedder(),
        verbose=False,
    )
    yield system
    system.close()


def test_fact_extraction_creates_node(ms):
    ms.start_conversation()
    ms.add_to_short_term("I really love Python!", "episodic", 0.7)
    ms.end_conversation()

    nodes, _ = ms.buffer.size()
    assert nodes == 1
    node = ms.buffer.get_node("node_1")
    assert node is not None
    assert node.content == FACT["content"]
    assert node.shard_key == "learning"
    # one decay pass: 0.2 + (0.9 - 0.2) * 0.99
    assert node.salience == pytest.approx(0.893, abs=1e-5)


def test_duplicate_fact_merges_not_duplicates(ms):
    ms.start_conversation()
    ms.add_to_short_term("I really love Python!", "episodic", 0.7)
    ms.end_conversation()

    ms.start_conversation()
    ms.add_to_short_term("Did I mention I love Python?", "episodic", 0.7)
    ms.end_conversation()

    nodes, _ = ms.buffer.size()
    assert nodes == 1  # merged, not duplicated
    node = ms.buffer.get_node("node_1")
    assert node.access_count == 1  # merge bumps access
    # merge restored salience to max(0.893, 0.9)=0.9, then decay → 0.893
    assert node.salience == pytest.approx(0.893, abs=1e-5)


def test_search_memories_finds_consolidated_fact(ms):
    ms.start_conversation()
    ms.add_to_short_term("I really love Python!", "episodic", 0.7)
    ms.end_conversation()

    results = ms.search_memories("User loves the Python programming language")
    assert len(results) == 1
    assert results[0].id == "node_1"
