"""Decoder numerics vs a real ``transformers`` Gemma (random-init, built
locally — zero egress) and the ``from_hf`` weight mapping."""

# Compile-heavy (multi-second XLA compiles / 100k-row arenas): the
# default lane must stay inside a driver window; run the full lane
# with no -m filter for round gates.
pytestmark = __import__("pytest").mark.slow

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp

from lazzaro_tpu.models.llm import LanguageModel

VOCAB = 128


@pytest.fixture(scope="module")
def hf_model():
    cfg = transformers.GemmaConfig(
        vocab_size=VOCAB, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, max_position_embeddings=64, rope_theta=10000.0,
        attention_bias=False, hidden_act="gelu_pytorch_tanh",
        pad_token_id=0, bos_token_id=2, eos_token_id=1)
    torch.manual_seed(0)
    model = transformers.GemmaForCausalLM(cfg)
    model.eval()
    return model


def test_logits_match_hf(hf_model):
    lm = LanguageModel.from_hf(hf_model, max_seq=64)
    rng = np.random.RandomState(0)
    ids = rng.randint(3, VOCAB, (2, 12))
    with torch.no_grad():
        ref = hf_model(input_ids=torch.tensor(ids)).logits.numpy()
    positions = np.broadcast_to(np.arange(12)[None, :], (2, 12))
    ours, _ = lm.model.apply({"params": lm.params},
                             jnp.asarray(ids), jnp.asarray(positions))
    np.testing.assert_allclose(np.asarray(ours), ref, atol=3e-4, rtol=3e-4)


def test_greedy_continuation_matches_hf(hf_model):
    """Greedy argmax chains must agree token-for-token (KV-cache decode on
    our side vs full re-forward on HF's)."""
    lm = LanguageModel.from_hf(hf_model, max_seq=64)
    rng = np.random.RandomState(1)
    ids = list(rng.randint(3, VOCAB, (6,)))

    hf_ids = list(ids)
    with torch.no_grad():
        for _ in range(8):
            logits = hf_model(input_ids=torch.tensor([hf_ids])).logits
            hf_ids.append(int(logits[0, -1].argmax()))

    tokens = jnp.asarray([ids], jnp.int32)
    positions = jnp.arange(len(ids))[None, :]
    caches = lm._empty_cache(1)
    logits, caches = lm._prefill(lm.params, tokens, positions, caches)
    ours = list(ids)
    pos = len(ids)
    for _ in range(8):
        nxt = int(np.asarray(logits[0]).argmax())
        ours.append(nxt)
        logits, caches = lm._decode_one(
            lm.params, jnp.asarray([nxt], jnp.int32),
            jnp.asarray([pos], jnp.int32), caches)
        pos += 1
    assert ours == hf_ids


def test_from_hf_with_tokenizer_adapter(hf_model):
    """A minimal HF-style tokenizer drives generate() end to end."""
    class TinyTok:
        bos_token_id = 2
        eos_token_id = 1

        def encode(self, text, add_special_tokens=False):
            return [3 + (ord(c) % (VOCAB - 3)) for c in text[:16]]

        def decode(self, ids, skip_special_tokens=True):
            return "".join(chr(97 + (i % 26)) for i in ids)

    lm = LanguageModel.from_hf(hf_model, hf_tokenizer=TinyTok(), max_seq=64)
    assert lm.eos_id == 1
    out = lm.generate("hello", max_new_tokens=5)
    assert isinstance(out, str)
    with pytest.raises(ValueError, match="byte tokenizer"):
        lm.generate_json("extract:")


def test_from_hf_accepts_bf16_checkpoint(hf_model):
    """Gemma checkpoints load natively bf16; torch bf16 tensors have no
    .numpy(), so the mapping must go through .float()."""
    bf16 = transformers.GemmaForCausalLM(hf_model.config).to(torch.bfloat16)
    bf16.load_state_dict({k: v.to(torch.bfloat16)
                          for k, v in hf_model.state_dict().items()})
    lm = LanguageModel.from_hf(bf16, max_seq=64)
    rng = np.random.RandomState(2)
    ids = rng.randint(3, VOCAB, (1, 8))
    positions = np.arange(8)[None, :]
    ours, _ = lm.model.apply({"params": lm.params},
                             jnp.asarray(ids), jnp.asarray(positions))
    with torch.no_grad():
        ref = hf_model(input_ids=torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(np.asarray(ours), ref, atol=0.1, rtol=0.1)


def test_from_hf_rejects_non_gemma():
    cfg = transformers.BertConfig(vocab_size=50, hidden_size=16,
                                  num_hidden_layers=1, num_attention_heads=2,
                                  intermediate_size=32)
    torch.manual_seed(0)
    bert = transformers.BertModel(cfg)
    with pytest.raises(ValueError, match="gemma"):
        LanguageModel.from_hf(bert)


# ---------------------------------------------------------------- Gemma-2


@pytest.fixture(scope="module")
def hf_gemma2():
    """Random-init local Gemma-2 with every family feature on: softcapping,
    sandwich norms, alternating local/global attention, query_pre_attn_scalar.
    4 layers so BOTH sliding (0,2) and global (1,3) layers are exercised;
    sliding_window=8 < seq length so the window actually masks."""
    cfg = transformers.Gemma2Config(
        vocab_size=VOCAB, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, max_position_embeddings=64, rope_theta=10000.0,
        attention_bias=False, hidden_activation="gelu_pytorch_tanh",
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        sliding_window=8, query_pre_attn_scalar=16,
        pad_token_id=0, bos_token_id=2, eos_token_id=1)
    torch.manual_seed(0)
    model = transformers.Gemma2ForCausalLM(cfg)
    model.eval()
    return model


def test_gemma2_logits_match_hf(hf_gemma2):
    lm = LanguageModel.from_hf(hf_gemma2, max_seq=64)
    assert lm.cfg.attn_softcap == 50.0 and lm.cfg.final_softcap == 30.0
    assert lm.cfg.post_norms and lm.cfg.sliding_window == 8
    rng = np.random.RandomState(0)
    T = 20                                   # > sliding_window: window bites
    ids = rng.randint(3, VOCAB, (2, T))
    with torch.no_grad():
        ref = hf_gemma2(input_ids=torch.tensor(ids)).logits.numpy()
    positions = np.broadcast_to(np.arange(T)[None, :], (2, T))
    ours, _ = lm.model.apply({"params": lm.params},
                             jnp.asarray(ids), jnp.asarray(positions))
    np.testing.assert_allclose(np.asarray(ours), ref, atol=1e-4, rtol=1e-4)


def test_gemma2_greedy_continuation_matches_hf(hf_gemma2):
    """KV-cache decode (with the sliding-window mask applied against cache
    positions) chains identically to HF's full re-forward."""
    lm = LanguageModel.from_hf(hf_gemma2, max_seq=64)
    rng = np.random.RandomState(1)
    ids = list(rng.randint(3, VOCAB, (10,)))

    hf_ids = list(ids)
    with torch.no_grad():
        for _ in range(8):
            logits = hf_gemma2(input_ids=torch.tensor([hf_ids])).logits
            hf_ids.append(int(logits[0, -1].argmax()))

    tokens = jnp.asarray([ids], jnp.int32)
    positions = jnp.arange(len(ids))[None, :]
    caches = lm._empty_cache(1)
    logits, caches = lm._prefill(lm.params, tokens, positions, caches)
    ours = list(ids)
    pos = len(ids)
    for _ in range(8):
        nxt = int(np.asarray(logits[0]).argmax())
        ours.append(nxt)
        logits, caches = lm._decode_one(
            lm.params, jnp.asarray([nxt], jnp.int32),
            jnp.asarray([pos], jnp.int32), caches)
        pos += 1
    assert ours == hf_ids


def test_gemma1_still_rejected_families(hf_model):
    class FakeCfg:
        model_type = "llama"

    class FakeModel:
        config = FakeCfg()

    with pytest.raises(ValueError, match="gemma"):
        LanguageModel.from_hf(FakeModel())
