"""Ulysses all-to-all sequence parallelism vs the dense causal oracle."""

# Compile-heavy (multi-second XLA compiles / 100k-row arenas): the
# default lane must stay inside a driver window; run the full lane
# with no -m filter for round gates.
pytestmark = __import__("pytest").mark.slow

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from lazzaro_tpu.parallel.mesh import make_mesh
from lazzaro_tpu.parallel.ring_attention import reference_causal_attention
from lazzaro_tpu.parallel.ulysses import make_ulysses_attention


def _sharded_qkv(mesh, B, T, H, D, seed=0):
    rng = np.random.RandomState(seed)
    q, k, v = (rng.randn(B, T, H, D).astype(np.float32) for _ in range(3))
    sh = NamedSharding(mesh, P(None, "sp", None, None))
    return ([jax.device_put(x, sh) for x in (q, k, v)],
            [jnp.asarray(x) for x in (q, k, v)])


@pytest.mark.parametrize("n,B,T,H,D", [(8, 1, 64, 8, 16), (4, 2, 32, 8, 8),
                                       (2, 1, 16, 2, 4)])
def test_matches_dense_causal(n, B, T, H, D):
    mesh = make_mesh(("sp",), (n,), devices=jax.devices()[:n])
    (qs, ks, vs), (q, k, v) = _sharded_qkv(mesh, B, T, H, D)
    out = make_ulysses_attention(mesh, "sp")(qs, ks, vs)
    ref = reference_causal_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_output_keeps_sequence_sharding():
    mesh = make_mesh(("sp",), (8,), devices=jax.devices()[:8])
    (qs, ks, vs), _ = _sharded_qkv(mesh, 1, 64, 8, 16)
    out = make_ulysses_attention(mesh, "sp")(qs, ks, vs)
    assert out.sharding.spec == P(None, "sp", None, None)


def test_rejects_indivisible_heads():
    mesh = make_mesh(("sp",), (8,), devices=jax.devices()[:8])
    (qs, ks, vs), _ = _sharded_qkv(mesh, 1, 64, 4, 16)   # 4 heads, 8 devices
    with pytest.raises(ValueError, match="divisible"):
        make_ulysses_attention(mesh, "sp")(qs, ks, vs)


def test_rejects_gqa_kv():
    mesh = make_mesh(("sp",), (2,), devices=jax.devices()[:2])
    rng = np.random.RandomState(0)
    sh = NamedSharding(mesh, P(None, "sp", None, None))
    q = jax.device_put(rng.randn(1, 16, 4, 8).astype(np.float32), sh)
    kv = jax.device_put(rng.randn(1, 16, 2, 8).astype(np.float32), sh)
    with pytest.raises(ValueError, match="MHA"):
        make_ulysses_attention(mesh, "sp")(q, kv, kv)


def test_agrees_with_ring_attention():
    """The two sequence-parallel schemes are interchangeable on MHA shapes."""
    from lazzaro_tpu.parallel.ring_attention import make_ring_attention

    mesh = make_mesh(("sp",), (8,), devices=jax.devices()[:8])
    (qs, ks, vs), _ = _sharded_qkv(mesh, 2, 64, 8, 16, seed=3)
    uly = make_ulysses_attention(mesh, "sp")(qs, ks, vs)
    ring = make_ring_attention(mesh, "sp")(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(uly), np.asarray(ring),
                               atol=1e-4, rtol=1e-4)
