"""Zero-copy mutation discipline (donated arena buffers).

Every mutation kernel donates its state so XLA scatters in place — no
full-arena HBM copy per small write. These tests pin the three contracts:
(a) donated kernels genuinely alias (pointer-stable buffers) and consume
their input; (b) the ``*_copy`` twins genuinely copy; (c) MemoryIndex's
refcount-gated ownership handoff donates on the sole-owner hot path but
falls back to copying whenever a reader still holds a snapshot — so no
live reference ever outlives a donated buffer.
"""

import numpy as np
import jax.numpy as jnp

from lazzaro_tpu.core import state as S
from lazzaro_tpu.core.index import MemoryIndex


def _add_args(b=8, d=16):
    return (jnp.full((b,), 0.5), jnp.zeros((b,)),
            jnp.zeros((b,), jnp.int32), jnp.zeros((b,), jnp.int32),
            jnp.zeros((b,), jnp.int32), jnp.zeros((b,), bool))


def test_donated_kernels_alias_and_consume():
    arena = S.init_arena(255, 16)
    rows = jnp.arange(8, dtype=jnp.int32)
    emb = jnp.ones((8, 16))
    p_emb = arena.emb.unsafe_buffer_pointer()
    p_sal = arena.salience.unsafe_buffer_pointer()
    arena2 = S.arena_add(arena, rows, emb, *_add_args())
    # in-place: both the scattered leaf and the pass-through leaves keep
    # their buffers
    assert arena2.emb.unsafe_buffer_pointer() == p_emb
    assert arena2.salience.unsafe_buffer_pointer() == p_sal
    # and the input was consumed
    assert arena.emb.is_deleted()

    edges = S.init_edges(255)
    p_src = edges.src.unsafe_buffer_pointer()
    edges2 = S.edges_add(edges, rows, rows, rows, jnp.full((8,), 0.5),
                         jnp.ones((8,), jnp.int32), jnp.float32(0.0),
                         jnp.int32(0), jnp.ones((8,), bool))
    assert edges2.src.unsafe_buffer_pointer() == p_src
    assert edges.src.is_deleted()


def test_copy_twins_do_not_consume():
    arena = S.init_arena(255, 16)
    rows = jnp.arange(8, dtype=jnp.int32)
    arena2 = S.arena_add_copy(arena, rows, jnp.ones((8, 16)), *_add_args())
    assert not arena.emb.is_deleted()
    assert (arena2.emb.unsafe_buffer_pointer()
            != arena.emb.unsafe_buffer_pointer())
    # the original is still fully usable
    assert not np.asarray(arena.alive)[:8].any()
    assert np.asarray(arena2.alive)[:8].all()


def _small_index():
    idx = MemoryIndex(dim=16, capacity=255)
    emb = np.eye(16, dtype=np.float32)[:4]
    idx.add(["a", "b", "c", "d"], emb, [0.5] * 4, [0.0] * 4,
            ["semantic"] * 4, ["default"] * 4, "u")
    return idx


def test_index_mutations_donate_on_sole_owner_path():
    """The hot single-writer path must alias, not copy: the arena buffer
    pointer is stable across every metadata mutation."""
    idx = _small_index()
    p_emb = idx.state.emb.unsafe_buffer_pointer()    # transient snapshot
    idx.update_access(["a"], now=1.0)
    idx.boost(["b"], now=2.0)
    idx.merge_touch(["c"], [0.9], now=3.0)
    idx.decay("u", 0.01)
    idx.delete(["d"])
    assert idx.state.emb.unsafe_buffer_pointer() == p_emb
    # edge arena too
    idx.add_edges([("a", "b", 0.7)], "u")
    p_src = idx.edge_state.src.unsafe_buffer_pointer()
    idx.add_edges([("b", "c", 0.6)], "u")
    idx.add_edges([("a", "b", 0.7)], "u")            # reinforce path
    assert idx.edge_state.src.unsafe_buffer_pointer() == p_src


def test_reader_snapshot_forces_copy_and_stays_usable():
    """A concurrent reader's snapshot must survive a writer's mutation:
    the ownership gate sees the raised refcount and runs the copying twin."""
    idx = _small_index()
    snap = idx.state                                  # reader holds the state
    before = np.asarray(snap.salience).copy()
    idx.update_access(["a"], boost=0.2, now=5.0)      # writer mutates
    # the snapshot was NOT donated out from under the reader
    assert not snap.emb.is_deleted()
    np.testing.assert_array_equal(np.asarray(snap.salience), before)
    # and the index really advanced past it
    row = idx.id_to_row["a"]
    assert int(np.asarray(idx.state.access_count)[row]) == 1
    assert float(np.asarray(idx.state.salience)[row]) > float(before[row])
    del snap
    # with the reader gone, the next mutation donates in place again
    p = idx.state.emb.unsafe_buffer_pointer()
    idx.boost(["b"], now=6.0)
    assert idx.state.emb.unsafe_buffer_pointer() == p


def test_fused_ingest_donates_both_states():
    idx = _small_index()
    idx.add_edges([("a", "b", 0.7)], "u")
    p_emb = idx.state.emb.unsafe_buffer_pointer()
    p_src = idx.edge_state.src.unsafe_buffer_pointer()
    emb = np.eye(16, dtype=np.float32)[4:8]
    rows, cands, created = idx.ingest_batch(
        ["e", "f", "g", "h"], emb, [0.5] * 4, [0.0] * 4,
        ["semantic"] * 4, ["default"] * 4, "u",
        chain_pairs=[("e", "f"), ("f", "g")])
    assert idx.state.emb.unsafe_buffer_pointer() == p_emb
    assert idx.edge_state.src.unsafe_buffer_pointer() == p_src
    assert len(rows) == 4
    # chain edges registered against real slots
    assert ("e", "f") in idx.edge_slots and ("f", "g") in idx.edge_slots
