"""Native host runtime: C++/Python parity + WAL durability semantics.

Mirrors the reference's "deterministic, exactly-testable" style (SURVEY §4):
the native paths must be bit-identical (tokenizer) or numerically identical
(top-k) to their Python fallbacks, and the WAL must survive torn tails.
"""

import hashlib
import os

import numpy as np
import pytest

from lazzaro_tpu import native
from lazzaro_tpu.models.tokenizer import HashTokenizer

requires_native = pytest.mark.skipif(not native.available(),
                                     reason="no C++ toolchain")


# ---------------------------------------------------------------------------
# blake2b + tokenizer parity
# ---------------------------------------------------------------------------


@requires_native
def test_blake2b8_matches_hashlib():
    for data in [b"", b"a", b"hello world", b"x" * 127, b"y" * 128,
                 b"z" * 129, b"w" * 1000, bytes(range(256)) * 5]:
        expect = int.from_bytes(
            hashlib.blake2b(data, digest_size=8).digest(), "little")
        assert native.blake2b8(data) == expect, f"len={len(data)}"


@requires_native
def test_encode_batch_matches_python_tokenizer():
    texts = [
        "Hello World, this is a TEST of tokenization!",
        "",
        "   ",
        "user likes python3 and JAX; TPU v5e-8",
        "a" * 500,                      # truncation past max_len
        "one-two_three.four",
        "ALLCAPS lower 12345 mIxEd",
    ]
    tok = HashTokenizer(vocab_size=4096, max_len=32)
    # Expected values MUST come from the pure-Python per-row encoder —
    # batch_encode itself routes through the native path when built.
    expect = np.asarray([tok.encode(t) for t in texts], np.int32)
    got = native.encode_batch(texts, 4096, 32)
    np.testing.assert_array_equal(got, expect)


@requires_native
def test_encode_batch_tiny_max_len():
    tok = HashTokenizer(vocab_size=256, max_len=8)
    for max_len in (1, 2, 3):
        expect = np.asarray([tok.encode("alpha beta", max_len)], np.int32)
        got = native.encode_batch(["alpha beta"], 256, max_len)
        np.testing.assert_array_equal(got, expect)


def test_encode_batch_non_ascii_falls_back():
    texts = ["héllo wörld", "日本語テキスト", "plain ascii"]
    tok = HashTokenizer(vocab_size=1024, max_len=16)
    expect = np.asarray([tok.encode(t) for t in texts], np.int32)
    got = native.encode_batch(texts, 1024, 16)
    np.testing.assert_array_equal(got, expect)


# ---------------------------------------------------------------------------
# masked top-k parity
# ---------------------------------------------------------------------------


@requires_native
def test_masked_topk_matches_numpy():
    rng = np.random.RandomState(0)
    n, d, k = 5000, 64, 10
    emb = rng.randn(n, d).astype(np.float32)
    emb[17] = 0.0                      # zero-norm row must never be returned
    alive = rng.rand(n) > 0.3
    query = rng.randn(d).astype(np.float32)
    s_native, r_native = native.masked_topk(emb, alive, query, k)
    s_numpy, r_numpy = native._topk_numpy(emb, alive, query, k)
    np.testing.assert_array_equal(r_native, r_numpy)
    np.testing.assert_allclose(s_native, s_numpy, rtol=1e-5)
    assert 17 not in r_native


@requires_native
def test_masked_topk_fewer_alive_than_k():
    emb = np.eye(3, 8, dtype=np.float32)
    alive = np.array([True, False, True])
    scores, rows = native.masked_topk(emb, alive, emb[0], k=5)
    assert rows[0] == 0 and set(rows[:2]) == {0, 2}
    assert list(rows[2:]) == [-1, -1, -1]


def test_masked_topk_numpy_fallback_shapes():
    s, r = native._topk_numpy(np.zeros((0, 4), np.float32), None,
                              np.ones(4, np.float32), 3)
    assert list(r) == [-1, -1, -1]


@requires_native
def test_masked_topk_multithreaded_large():
    rng = np.random.RandomState(1)
    n, d, k = 200_000, 32, 7          # crosses the 64k/thread threshold
    emb = rng.randn(n, d).astype(np.float32)
    query = rng.randn(d).astype(np.float32)
    s1, r1 = native.masked_topk(emb, None, query, k, nthreads=4)
    s2, r2 = native._topk_numpy(emb, None, query, k)
    np.testing.assert_array_equal(r1, r2)


# ---------------------------------------------------------------------------
# WAL
# ---------------------------------------------------------------------------


def test_wal_roundtrip(tmp_path):
    wal = native.WriteAheadLog(str(tmp_path / "j.wal"))
    payloads = [b"first", b"", b"third record with more bytes"]
    for p in payloads:
        wal.append(p)
    assert wal.replay() == payloads
    wal.reset()
    assert wal.replay() == []


def test_wal_missing_file(tmp_path):
    wal = native.WriteAheadLog(str(tmp_path / "nope.wal"))
    assert wal.replay() == []


def test_wal_torn_tail_discarded(tmp_path):
    path = str(tmp_path / "torn.wal")
    wal = native.WriteAheadLog(path)
    wal.append(b"good-1")
    wal.append(b"good-2")
    size_before = os.path.getsize(path)
    wal.append(b"the-final-record-that-gets-torn")
    with open(path, "r+b") as f:                 # crash mid-append
        f.truncate(size_before + 7)
    assert wal.replay() == [b"good-1", b"good-2"]


def test_wal_corrupt_payload_discarded(tmp_path):
    path = str(tmp_path / "corrupt.wal")
    wal = native.WriteAheadLog(path)
    wal.append(b"alpha")
    wal.append(b"beta")
    with open(path, "r+b") as f:                 # flip a byte in record 2
        data = bytearray(f.read())
        data[-1] ^= 0xFF
        f.seek(0)
        f.write(data)
    assert wal.replay() == [b"alpha"]


@requires_native
def test_wal_native_and_python_interchange(tmp_path, monkeypatch):
    """A log written by the native path replays via the Python path and
    vice versa — same on-disk format."""
    path = str(tmp_path / "mixed.wal")
    native.WriteAheadLog(path).append(b"written-native")

    import importlib
    build_mod = importlib.import_module("lazzaro_tpu.native.build")
    monkeypatch.setattr(build_mod, "_LIB", None)
    monkeypatch.setattr(build_mod, "_TRIED", True)
    py_wal = native.WriteAheadLog(path)
    assert py_wal.replay() == [b"written-native"]
    py_wal.append(b"written-python")

    monkeypatch.setattr(build_mod, "_TRIED", False)
    assert native.WriteAheadLog(path).replay() == [b"written-native",
                                                   b"written-python"]
