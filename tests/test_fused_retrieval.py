"""Fused single-dispatch retrieval (tier-1 smoke, CPU, tiny arena).

The per-chat-turn serving sequence — super-node top-1 gate, main-arena ANN
top-k, CSR neighbor gather, neighbor- + access-salience boosts — must run
as ONE device program (``state.search_fused``) with ONE packed readback.
These tests count the actual jit entry points during end-to-end ``chat()``
turns and pin exact semantic parity (ids, ordering, boost effects) with the
classic multi-dispatch path across super-gate hit, super-gate miss, and
empty-graph cases — mirroring ``test_fused_ingest.py`` for the serving side.
"""

import json
import tempfile

import numpy as np
import pytest

from lazzaro_tpu.config import MemoryConfig
from lazzaro_tpu.core import state as S
from lazzaro_tpu.core.memory_system import MemorySystem
from tests.test_fused_ingest import ClusteredEmb, QueueLLM

D = 24


def _system(tmp, serve_fused=True, per=20, super_threshold=100):
    ms = MemorySystem(
        enable_async=False, db_dir=tmp, verbose=False, load_from_disk=False,
        llm_provider=QueueLLM(per), embedding_provider=ClusteredEmb(),
        auto_prune=False, max_buffer_size=10_000,
        super_node_threshold=super_threshold,
        config=MemoryConfig(journal=False, auto_consolidate=False,
                            decay_rate=0.0))
    ms.config.serve_fused = serve_fused
    return ms


def _ingest(ms, convs=2):
    for c in range(convs):
        ms.start_conversation()
        ms.add_to_short_term(f"conv {c}", "episodic", 0.7)
        ms.end_conversation()
    return ms


_COUNTED = ("search_fused", "search_fused_copy", "search_fused_read",
            "search_fused_ragged", "search_fused_ragged_copy",
            "search_fused_ragged_read",
            "arena_search", "arena_update_access", "arena_update_access_copy",
            "arena_boost", "arena_boost_copy", "arena_apply_boosts",
            "arena_apply_boosts_copy")


def _count_dispatches(monkeypatch):
    calls = {name: 0 for name in _COUNTED}
    for name in _COUNTED:
        orig = getattr(S, name)

        def wrapped(*a, __orig=orig, __name=name, **kw):
            calls[__name] += 1
            return __orig(*a, **kw)

        monkeypatch.setattr(S, name, wrapped)
    return calls


def test_one_fused_dispatch_per_chat_turn(monkeypatch):
    """The jit-call counter: a chat turn's retrieval (gate + ANN + neighbor
    boost + access boost) costs exactly ONE device dispatch — the donated
    ragged ``search_fused_ragged`` program (ISSUE 7: per-query k rides as
    device data) — and zero classic search/boost dispatches."""
    with tempfile.TemporaryDirectory() as tmp:
        ms = _ingest(_system(tmp))
        ms.start_conversation()
        calls = _count_dispatches(monkeypatch)
        ms.chat("fact 7 body")
        assert calls["search_fused_ragged"] == 1  # donated single-writer
        for name in _COUNTED:
            if name != "search_fused_ragged":
                assert calls[name] == 0, (name, calls)
        ms.close()


def test_search_memories_takes_readonly_twin(monkeypatch):
    """A pure read (no boosts requested anywhere in the batch) must take
    the ragged read twin — same compute, no donation dance, ONE dispatch."""
    with tempfile.TemporaryDirectory() as tmp:
        ms = _ingest(_system(tmp))
        calls = _count_dispatches(monkeypatch)
        hits = ms.search_memories("fact 3 body")
        assert hits
        assert calls["search_fused_ragged_read"] == 1
        assert calls["search_fused_ragged"] == 0
        assert calls["search_fused"] == calls["search_fused_copy"] == 0
        assert calls["arena_search"] == 0
        # a whole fleet is still one dispatch
        ms.search_memories_batch([f"fact {i} body" for i in range(8)])
        assert calls["search_fused_ragged_read"] == 2
        ms.close()


def test_cached_hit_turn_pays_zero_device_dispatches(monkeypatch):
    """Satellite fix: a query-cache hit used to pay the full device boost
    sequence anyway. Now the cached turn queues boost counts host-side
    (ZERO dispatches) and ``end_conversation`` flushes them as ONE
    ``arena_apply_boosts`` scatter before decay."""
    with tempfile.TemporaryDirectory() as tmp:
        ms = _ingest(_system(tmp))
        ms.start_conversation()
        ms.chat("fact 7 body")                 # populates the query cache
        calls = _count_dispatches(monkeypatch)
        ms.chat("fact 7 body")                 # cache hit
        for name in _COUNTED:
            assert calls[name] == 0, (name, calls)
        assert ms._pending_boosts              # counts queued, not dropped
        ms.end_conversation()
        assert calls["arena_apply_boosts"] == 1
        assert not ms._pending_boosts
        ms.close()


def _numeric_cols(ms):
    cols = ms.index.pull_numeric()
    n = len(ms.index.id_to_row)
    return {k: cols[k][: n + 2] for k in ("salience", "access_count")}


def test_fused_matches_classic_chat_turns():
    """Ids, ordering, and boost side effects (salience + access counts on
    the arena AND host copies) identical across fused and classic serving
    for plain ANN turns — including repeated (cached) turns."""
    def build():
        return _ingest(_system(tempfile.mkdtemp(), serve_fused=True)), \
            _ingest(_system(tempfile.mkdtemp(), serve_fused=False))

    a, b = build()
    try:
        a.start_conversation()
        b.start_conversation()
        for q in ("fact 3 body", "fact 17 body", "fact 31 body",
                  "fact 3 body"):             # last one is a cache hit
            ra = a.chat(q)
            rb = b.chat(q)
            assert ra == rb
        a.end_conversation()
        b.end_conversation()
        ca, cb = _numeric_cols(a), _numeric_cols(b)
        np.testing.assert_allclose(ca["salience"], cb["salience"], atol=1e-6)
        np.testing.assert_array_equal(ca["access_count"], cb["access_count"])
        ha = {n: (round(a.buffer.nodes[n].salience, 5),
                  a.buffer.nodes[n].access_count) for n in a.buffer.nodes}
        hb = {n: (round(b.buffer.nodes[n].salience, 5),
                  b.buffer.nodes[n].access_count) for n in b.buffer.nodes}
        assert ha == hb
    finally:
        a.close()
        b.close()


def test_fused_matches_classic_super_gate_hit():
    """When the super-node gate fires, the kernel reports ``fast`` and skips
    device boosts; the host runs the identical hierarchy-children fast path
    and classic boosts — results and arena numerics must match exactly."""
    def build(serve_fused):
        ms = _ingest(_system(tempfile.mkdtemp(), serve_fused=serve_fused,
                             super_threshold=5))
        assert ms.super_nodes                  # threshold 5 < ~13 per shard
        return ms

    a, b = build(True), build(False)
    try:
        # query ON a super centroid: guaranteed > 0.4 gate
        sid = sorted(a.super_nodes)[0]
        centroid = np.asarray(a.super_nodes[sid].embedding, np.float32)
        ids_a, mode_a = a._retrieve_for_chat(centroid.tolist(), "probe-q")
        ids_b, mode_b = b._retrieve_for_chat(centroid.tolist(), "probe-q")
        assert ids_a == ids_b
        assert mode_a == "classic"             # device skipped boosts
        assert mode_b == "classic"
        # the fast-path signature: children served in child-list order
        children = a.super_nodes[sid].child_ids
        assert ids_a[0] == children[0]
        # full turns agree on the numerics too
        a.start_conversation()
        b.start_conversation()
        a.chat("fact 5 body")
        b.chat("fact 5 body")
        ca, cb = _numeric_cols(a), _numeric_cols(b)
        np.testing.assert_allclose(ca["salience"], cb["salience"], atol=1e-6)
        np.testing.assert_array_equal(ca["access_count"], cb["access_count"])
    finally:
        a.close()
        b.close()


def test_fused_matches_classic_empty_graph():
    """A fresh system (no nodes at all) serves empty results identically on
    both paths and never crashes in the kernel."""
    a = _system(tempfile.mkdtemp(), serve_fused=True)
    b = _system(tempfile.mkdtemp(), serve_fused=False)
    try:
        ids_a, _ = a._retrieve_for_chat(ClusteredEmb().embed("fact 1 body"),
                                        "fact 1 body")
        ids_b, _ = b._retrieve_for_chat(ClusteredEmb().embed("fact 1 body"),
                                        "fact 1 body")
        assert ids_a == ids_b == []
        assert a.search_memories("anything") == []
    finally:
        a.close()
        b.close()


def test_scheduler_coalesces_concurrent_turns():
    """Concurrent retrievals from many threads share device batches: the
    scheduler's flush policy coalesces them, and every caller still gets
    its own correct result (per-request demux)."""
    import threading

    with tempfile.TemporaryDirectory() as tmp:
        ms = _ingest(_system(tmp))
        expected = {q: [n.id for n in ms.search_memories(q)]
                    for q in (f"fact {i} body" for i in range(8))}
        # hold the worker hostage so submissions pile up into one batch
        results = {}

        def worker(q):
            results[q] = [n.id for n in ms.search_memories(q)]

        threads = [threading.Thread(target=worker, args=(q,))
                   for q in expected]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == expected
        stats = ms.query_scheduler.stats()
        assert stats["requests_served"] >= 2 * len(expected)
        ms.close()


def test_multi_tenant_batch_isolation():
    """One coalesced batch serving several tenants keeps isolation: the
    per-request tenant column masks rows inside the kernel."""
    with tempfile.TemporaryDirectory() as tmp:
        ms = _ingest(_system(tmp))
        emb = ClusteredEmb()
        # second tenant's rows go straight into the index
        ms.index.add(["t2:alien_1"], np.asarray([emb.embed("fact 3 body")],
                                                np.float32),
                     [0.9], [0.0], ["semantic"], ["default"], "t2")
        from lazzaro_tpu.serve import RetrievalRequest
        reqs = [
            RetrievalRequest(query=np.asarray(emb.embed("fact 3 body"),
                                              np.float32),
                             tenant=ms.user_id, k=5),
            RetrievalRequest(query=np.asarray(emb.embed("fact 3 body"),
                                              np.float32),
                             tenant="t2", k=5),
        ]
        res = ms.index.search_fused_requests(
            reqs, cap_take=5, max_nbr=8, super_gate=0.4,
            acc_boost=0.05, nbr_boost=0.02)
        assert res[0].ids and all(i.startswith(f"{ms.user_id}:")
                                  for i in res[0].ids)
        assert res[1].ids == ["t2:alien_1"]
        ms.close()


def test_fused_serving_covers_every_mode():
    """Since ISSUE 3 the fused path serves int8 mode itself (the quantized
    coarse-scan + exact-rescore kernel), since ISSUE 4 the IVF coarse
    stage rides INSIDE the fused program too (centroid prefilter + member
    gather, ``search_fused_ivf``), and since ISSUE 16 PQ member storage
    joined as well (``search_fused_pq`` — in-kernel ADC member scan +
    exact rescore) — no mode opts out of fusion anymore."""
    with tempfile.TemporaryDirectory() as tmp:
        ms = _ingest(_system(tmp))
        assert ms._use_fused_serving()
        ms.index.int8_serving = True
        assert ms._use_fused_serving()     # quant kernel serves this mode
        ms.index.int8_serving = False
        ms.index.ivf_nprobe = 4
        assert ms._use_fused_serving()     # IVF rides the fused kernel now
        ms.index.pq_serving = True
        assert ms._use_fused_serving()     # PQ rides it too (ISSUE 16)
        ms.config.serve_fused = False
        assert not ms._use_fused_serving()  # only the config opts out
        ms.close()
