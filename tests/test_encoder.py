"""Flax text encoder: determinism, normalization, batching, graft hooks."""

import numpy as np
import pytest

from lazzaro_tpu.models.encoder import EncoderConfig, TextEncoder
from lazzaro_tpu.models.tokenizer import HashTokenizer


@pytest.fixture(scope="module")
def enc():
    return TextEncoder(EncoderConfig.tiny(), seed=0)


def test_hash_tokenizer_deterministic():
    tok = HashTokenizer(vocab_size=1024, max_len=16)
    a = tok.encode("The quick brown fox")
    b = tok.encode("The quick brown fox")
    assert a == b
    assert len(a) == 16
    assert a[0] == 1  # CLS


def test_encoder_outputs_normalized(enc):
    v = enc.encode("hello world")
    assert v.shape == (enc.dim,)
    assert np.linalg.norm(v) == pytest.approx(1.0, abs=1e-4)


def test_encoder_deterministic_across_instances():
    a = TextEncoder(EncoderConfig.tiny(), seed=0).encode("same text")
    b = TextEncoder(EncoderConfig.tiny(), seed=0).encode("same text")
    assert np.allclose(a, b, atol=1e-6)


def test_batch_matches_single(enc):
    texts = ["alpha beta", "gamma delta", "epsilon"]
    batch = enc.encode_batch(texts)
    for i, t in enumerate(texts):
        assert np.allclose(batch[i], enc.encode(t), atol=1e-5)


def test_encoder_embedder_provider(enc):
    from lazzaro_tpu.core.providers import EncoderEmbedder
    p = EncoderEmbedder(enc)
    assert p.dim == enc.dim
    v = p.embed("test")
    assert len(v) == enc.dim
    assert len(p.batch_embed(["a", "b"])) == 2


def _load_graft():
    import importlib.util
    from pathlib import Path
    path = Path(__file__).resolve().parents[1] / "__graft_entry__.py"
    spec = importlib.util.spec_from_file_location("graft_entry", str(path))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


def test_graft_entry_compiles():
    import jax
    m = _load_graft()
    fn, args = m.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[-1] >= 259  # vocab logits


def test_dryrun_multichip_8():
    _load_graft().dryrun_multichip(8)
