"""CLI command handling, dashboard HTTP routes, and integrations."""

import json
import threading
import urllib.request

import pytest

from lazzaro_tpu import MemorySystem

from tests.fakes import MockEmbedder, MockLLM, extraction_response

FACT = {"content": "User builds TPU frameworks", "type": "semantic",
        "salience": 0.8, "topic": "work"}


def make_ms(tmp_db, **kw):
    llm = MockLLM(sniffers={
        "Extract distinct, atomic facts": extraction_response([FACT]),
        "Analyze these related memories": json.dumps(
            {"knowledge_domains": "TPU systems"}),
        "comprehensive psychological": "1. **Personality Traits**: focused.",
    }, response="assistant reply")
    defaults = dict(enable_async=False, auto_consolidate=False,
                    load_from_disk=False, db_dir=tmp_db,
                    llm_provider=llm, embedding_provider=MockEmbedder(),
                    verbose=False)
    defaults.update(kw)
    return MemorySystem(**defaults)


def ingest(ms):
    ms.start_conversation()
    ms.add_to_short_term("I build TPU frameworks", "episodic", 0.7)
    ms.end_conversation()


# ---------------------------------------------------------------- CLI


def test_cli_commands(tmp_db, capsys):
    from lazzaro_tpu.cli.main import handle_command
    ms = make_ms(tmp_db)
    ingest(ms)

    assert handle_command(ms, "/stats")
    assert "SCALABLE MEMORY SYSTEM STATS" in capsys.readouterr().out
    assert handle_command(ms, "/memories 5")
    assert "Stored Memories" in capsys.readouterr().out
    assert handle_command(ms, "/profile")
    capsys.readouterr()
    assert handle_command(ms, "/set max_buffer_size 99")
    assert ms.max_buffer_size == 99
    capsys.readouterr()
    assert handle_command(ms, "/set nonexistent 1")
    assert "Unknown parameter" in capsys.readouterr().out
    assert handle_command(ms, "/config")
    assert "max_buffer_size: 99" in capsys.readouterr().out
    # /quit returns False to stop the loop
    assert handle_command(ms, "/quit") is False
    ms.close()


def test_cli_save_load_work(tmp_db, tmp_path, capsys):
    """The reference CLI /save and /load crash on memory.persistence
    (cli/main.py:110,118) — ours must actually work."""
    from lazzaro_tpu.cli.main import handle_command
    ms = make_ms(tmp_db)
    ingest(ms)
    snap = str(tmp_path / "snap.json")
    assert handle_command(ms, f"/save {snap}")
    out = capsys.readouterr().out
    assert "State saved" in out

    ms2 = make_ms(str(tmp_path / "db2"))
    assert handle_command(ms2, f"/load {snap}")
    assert "State loaded" in capsys.readouterr().out
    assert ms2.buffer.size()[0] == 1
    ms.close()
    ms2.close()


# ---------------------------------------------------------- dashboard


@pytest.fixture()
def dashboard(tmp_db):
    from lazzaro_tpu.dashboard.api import make_server
    ms = make_ms(tmp_db)
    ingest(ms)
    server = make_server(ms, host="127.0.0.1", port=0)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{port}", ms
    server.shutdown()
    ms.close()


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as r:
        body = r.read().decode()
        return r.status, body


def _post(base, path, payload):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, r.read().decode()


def test_dashboard_routes(dashboard):
    base, ms = dashboard
    status, html = _get(base, "/")
    assert status == 200 and "lazzaro-tpu" in html

    status, body = _get(base, "/api/stats")
    stats = json.loads(body)
    assert stats["buffer_nodes"] == 1
    assert stats["user_id"] == "default"

    _, body = _get(base, "/api/graph")
    graph = json.loads(body)
    assert len(graph["nodes"]) == 1
    assert graph["nodes"][0]["content"] == FACT["content"]

    _, body = _get(base, "/api/profile")
    assert "profile" in json.loads(body)

    _, body = _get(base, "/api/export?format=json")
    exported = json.loads(json.loads(body)["content"])
    assert exported[0]["content"] == FACT["content"]

    _, body = _get(base, "/api/insights")
    assert "Personality" in json.loads(body)["insights"]

    _, body = _post(base, "/api/consolidate", {})
    assert json.loads(body)["status"] == "success"

    _, body = _post(base, "/api/users/switch", {"user_id": "bob"})
    assert json.loads(body)["user_id"] == "bob"
    _, body = _get(base, "/api/stats")
    assert json.loads(body)["buffer_nodes"] == 0  # bob is empty


def test_dashboard_error_paths(dashboard):
    base, _ = dashboard
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(base, "/api/nope")
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(base, "/api/users/switch", {})
    assert e.value.code == 400


# -------------------------------------------------------- integrations


def test_langchain_memory_roundtrip(tmp_db):
    from lazzaro_tpu.integrations.langchain_integration import LazzaroLangChainMemory
    ms = make_ms(tmp_db)
    ingest(ms)
    mem = LazzaroLangChainMemory(ms)
    out = mem.load_memory_variables({"input": "User builds TPU frameworks"})
    assert FACT["content"] in out["history"]
    mem.save_context({"input": "hello"}, {"output": "world"})
    assert len(ms.short_term_memory) == 2
    mem.clear()
    assert not ms.conversation_active
    ms.close()


def test_langgraph_nodes(tmp_db):
    from lazzaro_tpu.integrations.langgraph_integration import LazzaroLangGraph
    ms = make_ms(tmp_db)
    ingest(ms)
    lg = LazzaroLangGraph(ms)
    ctx = lg.get_memory_node()({"input": "User builds TPU frameworks"})
    assert FACT["content"] in ctx["lazzaro_context"]
    lg.get_record_node()({"messages": ["question?", "answer."]})
    assert len(ms.short_term_memory) == 2
    ms.close()


def test_adk_plugin(tmp_db):
    from lazzaro_tpu.integrations.adk_integration import LazzaroADKPlugin
    ms = make_ms(tmp_db)
    ingest(ms)
    plugin = LazzaroADKPlugin(ms)
    tool = plugin.as_tool()
    assert tool["name"] == "lazzaro_memory_retrieval"
    assert FACT["content"] in tool["func"]("User builds TPU frameworks")
    assert plugin.retrieve("zzz unrelated zzz qqq")  # never empty string
    plugin.observe("in", "out")
    assert len(ms.short_term_memory) == 2
    ms.close()


def test_integrations_module_guarded_imports():
    import lazzaro_tpu.integrations as integ
    # langgraph/adk integrations have no hard deps → always exported
    assert "LazzaroLangGraph" in integ.__all__
    assert "LazzaroADKPlugin" in integ.__all__


def test_dashboard_search_and_inspector_markup(dashboard):
    """The explorer's interactive affordances (parity with reference
    templates/index.html:105-110 search, :312-322 match+centerAt+zoom,
    :233-251/:363 node-click inspector) are present and wired."""
    base, _ = dashboard
    _, html = _get(base, "/")
    # search input wired to the match flow
    assert 'id="search"' in html
    assert "Search memories..." in html
    assert "searchNodes" in html
    # match + centerAt + zoom (3.5x, 1s — same targets as the reference)
    assert "centerAt(" in html
    assert "3.5" in html
    # click-to-inspect inspector panel with the reference's fields + neighbors
    assert 'id="inspector"' in html
    assert "selectNode" in html
    assert 'addEventListener("click"' in html
    for field in ("ins-content", "ins-salience", "ins-access", "ins-shard",
                  "ins-neighbors"):
        assert field in html
