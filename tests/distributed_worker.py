"""Worker for the two-process `jax.distributed` smoke test.

Run by tests/test_distributed.py as a subprocess pair:

    python distributed_worker.py <process_id> <num_processes> <coord_port>

Each process brings 4 virtual CPU devices (8 global), calls
``jax.distributed.initialize``, builds ``make_hybrid_mesh``, and drives the
two multi-host paths SURVEY §2.3 requires: the sharded top-k collective and
a data-parallel encoder train step. Prints one "DIST_OK ..." line on
success; any assertion kills the pair.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")

import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")


def main():
    pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=nprocs, process_id=pid)
    assert jax.process_count() == nprocs
    assert jax.device_count() == 4 * nprocs
    assert len(jax.local_devices()) == 4

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from lazzaro_tpu.parallel.mesh import make_hybrid_mesh
    from lazzaro_tpu.ops.topk import make_sharded_topk

    # CPU exposes no slice topology → one size-1 DCN axis over a single
    # 8-wide ICI group; consumers never special-case slice count.
    mesh = make_hybrid_mesh(("data",), (4 * nprocs,))
    assert mesh.shape["slice"] == 1 and mesh.shape["data"] == 4 * nprocs

    # ---- sharded top-k across both processes ----------------------------
    N, D, K = 512, 32, 8
    rng = np.random.default_rng(0)           # same data on every process
    emb = rng.standard_normal((N, D)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    mask = np.ones((N,), bool)
    mask[::7] = False
    query = rng.standard_normal((3, D)).astype(np.float32)

    mat_sh = NamedSharding(mesh, P("data", None))
    row_sh = NamedSharding(mesh, P("data"))
    emb_g = jax.make_array_from_callback(
        emb.shape, mat_sh, lambda idx: emb[idx])
    mask_g = jax.make_array_from_callback(
        mask.shape, row_sh, lambda idx: mask[idx])

    search = make_sharded_topk(mesh, axis="data", k=K)
    scores, rows = search(emb_g, mask_g, query)
    scores = np.asarray(scores)              # out_specs replicated → local
    rows = np.asarray(rows)

    ref = (query @ emb.T)
    ref[:, ~mask] = -np.inf
    ref_rows = np.argsort(-ref, axis=1)[:, :K]
    ref_scores = np.take_along_axis(ref, ref_rows, axis=1)
    assert np.allclose(np.sort(scores, axis=1),
                       np.sort(ref_scores, axis=1), atol=1e-5), "top-k scores"
    assert (np.sort(rows, axis=1) == np.sort(ref_rows, axis=1)).all(), "top-k rows"

    # ---- data-parallel encoder train step over the 2-process mesh -------
    import optax
    from lazzaro_tpu.models.encoder import (EncoderConfig, TextEncoder,
                                            make_encoder_train_step)

    cfg = EncoderConfig.tiny()
    enc = TextEncoder(cfg, seed=0)           # same seed → replicated params
    opt = optax.adam(1e-3)
    # DP over the hybrid mesh's ICI axis: works because the step only names
    # the 'data' axis and the size-1 'slice' axis shards nothing.
    step = make_encoder_train_step(cfg, opt, mesh=mesh)
    texts = [f"sentence number {i} about topic {i % 4}" for i in range(8)]
    para = [f"a paraphrase {i} of topic {i % 4}" for i in range(8)]
    q_ids = jnp.asarray(enc.tokenizer.batch_encode(texts, cfg.max_len), jnp.int32)
    p_ids = jnp.asarray(enc.tokenizer.batch_encode(para, cfg.max_len), jnp.int32)
    params, opt_state = enc.params, opt.init(enc.params)
    losses = []
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state, q_ids, p_ids)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses

    print(f"DIST_OK pid={pid} topk=pass loss0={losses[0]:.6f} "
          f"loss2={losses[-1]:.6f}", flush=True)
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
