"""Deep-consolidation merge at scale (VERDICT r3 weak #3 / next #3).

The old ``pairwise_merge_candidates`` materialized the full [N, N] score
matrix (~4 TB at 1M rows). The chunked rewrite streams [chunk, N] tiles via
``lax.map``; these tests pin (a) exact equivalence with a naive all-pairs
oracle on an awkward (non-multiple-of-chunk) size, and (b) that the merge
stage completes at 100k rows and finds exactly the planted duplicates —
the intended `_merge_similar_nodes` semantics (reference
memory_system.py:1065-1120, minus its last-node-only indentation bug).
"""

# Compile-heavy (multi-second XLA compiles / 100k-row arenas): the
# default lane must stay inside a driver window; run the full lane
# with no -m filter for round gates.
pytestmark = __import__("pytest").mark.slow

import numpy as np
import jax.numpy as jnp
import pytest

from lazzaro_tpu.core.index import MemoryIndex
from lazzaro_tpu.ops.graphops import pairwise_merge_candidates


def _naive_pairs(emb: np.ndarray, mask: np.ndarray, threshold: float, k: int):
    scores = emb @ emb.T
    n = emb.shape[0]
    out = set()
    for i in range(n):
        if not mask[i]:
            continue
        cand = [(scores[i, j], j) for j in range(i + 1, n)
                if mask[j] and scores[i, j] > threshold]
        for _, j in sorted(cand, reverse=True)[:k]:
            out.add((i, j))
    return out


def test_chunked_matches_naive_oracle():
    rng = np.random.default_rng(0)
    n, d = 1500, 24                       # deliberately not a chunk multiple
    emb = rng.standard_normal((n, d)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    # plant duplicate clusters across chunk boundaries
    for a, b in [(3, 700), (511, 512), (1023, 1499), (100, 101)]:
        emb[b] = emb[a]
    mask = np.ones(n, bool)
    mask[100] = False                     # masked rows must never appear
    ts, tj = pairwise_merge_candidates(
        jnp.asarray(emb), jnp.asarray(mask), jnp.float32(0.95), k=4, chunk=512)
    got = {(i, int(j)) for i in range(n) for j in np.asarray(tj)[i] if j >= 0}
    want = _naive_pairs(emb, mask, 0.95, k=4)
    assert got == want
    assert (3, 700) in got and (511, 512) in got and (1023, 1499) in got
    assert all(100 not in p for p in got)


def test_merge_candidates_100k_rows():
    rng = np.random.default_rng(1)
    n, d = 100_000, 32
    emb = rng.standard_normal((n, d)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    planted = [(10, 99_000), (4_095, 4_096), (50_000, 50_001)]
    for a, b in planted:
        emb[b] = emb[a]

    idx = MemoryIndex(dim=d, capacity=n + 8)
    ids = [f"m{i}" for i in range(n)]
    step = 20_000
    for s in range(0, n, step):
        sl = slice(s, s + step)
        idx.add(ids[sl], emb[sl], [0.5] * step, [1000.0] * step,
                ["semantic"] * step, ["default"] * step, "u1")

    pairs = idx.merge_candidates("u1", threshold=0.98)
    got = {tuple(sorted((a, b))) for a, b, _ in pairs}
    want = {tuple(sorted((f"m{a}", f"m{b}"))) for a, b in planted}
    assert got == want, f"extra/missing merge pairs: {got ^ want}"
    for _, _, sim in pairs:
        assert sim == pytest.approx(1.0, abs=5e-3)
