"""Failure detection: retries, sentinel detection, circuit breaker, fallback."""

import numpy as np
import pytest

from lazzaro_tpu.core.resilience import (
    CircuitBreaker, ResilientEmbedder, ResilientLLM)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class ScriptedLLM:
    """Yields scripted results; 'raise' raises, '' mimics the reference's
    swallowed-failure sentinel."""

    def __init__(self, script):
        self.script = list(script)
        self.calls = 0

    def completion(self, messages, response_format=None):
        self.calls += 1
        step = self.script.pop(0) if self.script else "ok"
        if step == "raise":
            raise ConnectionError("api down")
        return step

    def completion_stream(self, messages, response_format=None):
        out = self.completion(messages, response_format)
        for i in range(0, len(out), 4):
            yield out[i:i + 4]


class ScriptedEmbedder:
    dim = 8

    def __init__(self, script):
        self.script = list(script)

    def _next(self, n):
        step = self.script.pop(0) if self.script else "ok"
        if step == "raise":
            raise ConnectionError("api down")
        if step == "zeros":
            return [[0.0] * self.dim] * n
        if step == "partial":
            rows = [[1.0] + [0.0] * (self.dim - 1)] * n
            rows[0] = [0.0] * self.dim
            return rows
        return [[1.0] + [0.0] * (self.dim - 1)] * n

    def embed(self, text):
        return self._next(1)[0]

    def batch_embed(self, texts):
        return self._next(len(texts))


MSG = [{"role": "user", "content": "hello"}]


def test_breaker_state_machine():
    clock = FakeClock()
    br = CircuitBreaker(threshold=2, cooldown=10.0, clock=clock)
    assert br.state == "closed" and br.allow()
    br.record_failure()
    assert br.state == "closed"
    br.record_failure()
    assert br.state == "open" and not br.allow()
    clock.advance(10.0)
    assert br.state == "half-open" and br.allow()
    br.record_failure()                      # probe fails → re-open
    assert br.state == "open"
    clock.advance(10.0)
    br.record_success()
    assert br.state == "closed"


def test_retry_then_success():
    llm = ResilientLLM(ScriptedLLM(["raise", "recovered"]), max_retries=1)
    assert llm.completion(MSG) == "recovered"
    h = llm.health()
    assert h["primary_failures"] == 1 and h["fallback_calls"] == 0


def test_empty_sentinel_detected_and_falls_back():
    primary = ScriptedLLM(["", ""])          # reference-style silent failure
    llm = ResilientLLM(primary, max_retries=1)
    out = llm.completion(MSG)
    assert out                               # heuristic fallback answered
    assert llm.health()["fallback_calls"] == 1
    assert primary.calls == 2                # initial + one retry


def test_breaker_opens_and_skips_primary():
    clock = FakeClock()
    primary = ScriptedLLM(["raise"] * 10)
    llm = ResilientLLM(primary, max_retries=0, breaker_threshold=2,
                       cooldown=30.0, clock=clock)
    llm.completion(MSG)
    llm.completion(MSG)
    assert llm.health()["breaker_state"] == "open"
    calls_before = primary.calls
    llm.completion(MSG)                      # breaker open → straight to fallback
    assert primary.calls == calls_before
    clock.advance(30.0)                      # half-open → probe again
    primary.script = ["back online"]
    assert llm.completion(MSG) == "back online"
    assert llm.health()["breaker_state"] == "closed"


def test_stream_falls_back_on_error():
    llm = ResilientLLM(ScriptedLLM(["raise"]), max_retries=0)
    out = "".join(llm.completion_stream(MSG))
    assert out                               # fallback streamed something
    llm2 = ResilientLLM(ScriptedLLM(["streaming works fine"]))
    assert "".join(llm2.completion_stream(MSG)) == "streaming works fine"


def test_embedder_zero_vector_detected():
    emb = ResilientEmbedder(ScriptedEmbedder(["zeros", "zeros"]), max_retries=1)
    vec = emb.embed("hello world")
    assert np.abs(vec).sum() > 0             # fallback hashing embedding
    assert emb.health()["fallback_calls"] == 1


def test_embedder_partial_batch_repaired():
    emb = ResilientEmbedder(ScriptedEmbedder(["partial"]))
    out = emb.batch_embed(["a bad row", "a good row", "another good"])
    arr = np.asarray(out)
    assert arr.shape == (3, 8)
    assert np.all(np.abs(arr).sum(axis=1) > 0)   # zero row re-embedded


def test_embedder_dim_mismatch_rejected():
    class OtherDim:
        dim = 16

        def embed(self, text):
            return [0.0] * 16

        def batch_embed(self, texts):
            return [[0.0] * 16 for _ in texts]

    with pytest.raises(ValueError, match="dim"):
        ResilientEmbedder(ScriptedEmbedder([]), fallback=OtherDim())


def test_memory_system_with_resilient_providers(tmp_path):
    """End-to-end: a flaky primary LLM + embedder still produce a working
    ingest → retrieval cycle via fallbacks."""
    from lazzaro_tpu.core.memory_system import MemorySystem

    flaky_llm = ResilientLLM(ScriptedLLM(["raise"] * 50), max_retries=0,
                             breaker_threshold=2)
    flaky_emb = ResilientEmbedder(ScriptedEmbedder(["raise"] * 50),
                                  max_retries=0, breaker_threshold=2)
    ms = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db"),
                      verbose=False, load_from_disk=False,
                      llm_provider=flaky_llm, embedding_provider=flaky_emb)
    ms.start_conversation()
    ms.chat("I work as a data engineer on a big ETL project.")
    ms.end_conversation()
    hits = [n.content for n in ms.search_memories("data engineer work")]
    assert any("data engineer" in h for h in hits)
    assert flaky_llm.health()["fallback_calls"] > 0
    ms.close()


def test_degraded_raising_stays_inside_wrapper():
    """A malformed primary result that makes the degraded() check itself
    raise must count as a primary failure and land on the fallback, not
    escape the never-crash contract (advisor r1: resilience.py:105-113)."""
    class MalformedEmbedder:
        dim = 8

        def embed(self, text):
            return "not a vector"          # np.asarray(..., float32) raises

        def batch_embed(self, texts):
            return "not a matrix"

    emb = ResilientEmbedder(MalformedEmbedder(), max_retries=0)
    out = emb.embed("hello")
    assert len(out) == 8 and any(abs(x) > 0 for x in out)
    h = emb.health()
    assert h["primary_failures"] == 1
    assert h["fallback_calls"] == 1


def test_mid_stream_failure_counted_by_breaker():
    """A stream dying AFTER the first chunk can't be restarted; it must be
    visible to the breaker (advisor r1: resilience.py:150-160) AND to the
    caller — swallowing it would disguise truncated output as complete."""
    class MidStreamDeath:
        def completion(self, messages, response_format=None):
            return "fallback text"

        def completion_stream(self, messages, response_format=None):
            yield "first chunk "
            yield "second chunk "
            raise ConnectionError("died mid-stream")

    clock = FakeClock()
    llm = ResilientLLM(MidStreamDeath(), breaker_threshold=2, clock=clock)
    for _ in range(2):
        chunks = []
        with pytest.raises(ConnectionError):
            for c in llm.completion_stream(MSG):
                chunks.append(c)
        assert chunks == ["first chunk ", "second chunk "]
    h = llm.health()
    assert h["primary_failures"] == 2
    assert llm.breaker.state == "open"
    # While open, streaming goes straight to the fallback.
    out = "".join(llm.completion_stream(MSG))
    assert "first chunk" not in out


def test_clean_stream_closes_breaker():
    class GoodStream:
        def completion(self, messages, response_format=None):
            return "ok"

        def completion_stream(self, messages, response_format=None):
            yield "a"
            yield "b"

    llm = ResilientLLM(GoodStream(), breaker_threshold=2)
    llm.breaker.consecutive_failures = 1
    assert list(llm.completion_stream(MSG)) == ["a", "b"]
    assert llm.breaker.consecutive_failures == 0


def test_early_closed_healthy_stream_counts_as_success():
    """A caller abandoning a healthy stream (GeneratorExit) must reset the
    breaker, not leave failures pending."""
    class GoodStream:
        def completion(self, messages, response_format=None):
            return "ok"

        def completion_stream(self, messages, response_format=None):
            for t in ["a", "b", "c", "d"]:
                yield t

    llm = ResilientLLM(GoodStream(), breaker_threshold=3)
    llm.breaker.consecutive_failures = 2
    gen = llm.completion_stream(MSG)
    assert next(gen) == "a"
    gen.close()
    assert llm.breaker.consecutive_failures == 0
