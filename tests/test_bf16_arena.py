"""MemoryConfig.dtype reaches the arena: an orchestrator built with
dtype="bfloat16" keeps search / dedup / snapshot semantics intact while the
device embedding matrix is actually bf16 (half the HBM of the f32 default —
the knob the 1M-node target depends on)."""

import jax.numpy as jnp
import pytest

from lazzaro_tpu import MemorySystem
from lazzaro_tpu.config import MemoryConfig

from tests.fakes import MockEmbedder, MockLLM, extraction_response

FACT = {"content": "User loves the Python programming language",
        "type": "semantic", "salience": 0.9, "topic": "learning"}


@pytest.fixture()
def ms(tmp_db):
    llm = MockLLM(sniffers={
        "Extract distinct, atomic facts": extraction_response([FACT]),
    }, response="chat reply")
    system = MemorySystem(
        enable_async=False,
        auto_consolidate=False,
        load_from_disk=False,
        db_dir=tmp_db,
        llm_provider=llm,
        embedding_provider=MockEmbedder(),
        config=MemoryConfig(dtype="bfloat16"),
        verbose=False,
    )
    yield system
    system.close()


def test_arena_is_bf16(ms):
    assert ms.index.state.emb.dtype == jnp.bfloat16


def test_search_and_dedup_semantics_survive_bf16(ms):
    ms.start_conversation()
    ms.add_to_short_term("I really love Python!", "episodic", 0.7)
    ms.end_conversation()

    # duplicate conversation: the 0.95 dedup gate must still merge in bf16
    ms.start_conversation()
    ms.add_to_short_term("Did I mention I love Python?", "episodic", 0.7)
    ms.end_conversation()

    nodes, _ = ms.buffer.size()
    assert nodes == 1

    hits = ms.search_memories("python")
    assert [n.content for n in hits] == [FACT["content"]]


def test_bf16_snapshot_roundtrip(ms, tmp_path):
    ms.start_conversation()
    ms.add_to_short_term("I really love Python!", "episodic", 0.7)
    ms.end_conversation()
    snap = str(tmp_path / "snap")
    ms.save_snapshot(snap)

    ms.load_snapshot(snap)
    assert ms.index.state.emb.dtype == jnp.bfloat16
    assert [n.content for n in ms.search_memories("python")] == [FACT["content"]]
