"""ShardedMemoryIndex checkpoints: round trip + pod-shape portability."""

import jax
import numpy as np
import pytest

from lazzaro_tpu.core.checkpoint import (load_index, load_sharded_index,
                                         save_sharded_index)
from lazzaro_tpu.parallel.index import ShardedMemoryIndex
from lazzaro_tpu.parallel.mesh import make_mesh


def _mesh(n):
    return make_mesh(("data",), (n,), devices=jax.devices()[:n])


def _filled(mesh, n=24, d=16, capacity=64):
    idx = ShardedMemoryIndex(mesh, dim=d, capacity=capacity, k=5)
    rng = np.random.RandomState(0)
    emb = rng.randn(n, d).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    idx.add([f"n{i}" for i in range(n)], emb, "default",
            saliences=[0.4 + 0.01 * i for i in range(n)])
    idx.add(["a0", "a1"], rng.randn(2, d).astype(np.float32), "alice")
    return idx, emb


def test_round_trip_same_mesh(tmp_path):
    mesh = _mesh(8)
    idx, emb = _filled(mesh)
    ck = str(tmp_path / "ck")
    save_sharded_index(idx, ck)
    idx2 = load_sharded_index(ck, mesh, k=5)

    assert idx2.id_to_row == idx.id_to_row
    assert idx2._tenants == idx._tenants
    for q in emb[:5]:
        assert idx2.search(q, "default") == idx.search(q, "default")
    # Tenant isolation survives.
    ids_a, _ = idx2.search(emb[0], "alice")
    assert all(i.startswith("a") for i in ids_a)


def test_portable_across_pod_shapes(tmp_path):
    """A checkpoint from an 8-way mesh restores onto a 4-way mesh (axis
    size divides the saved capacity) with identical results."""
    idx, emb = _filled(_mesh(8), capacity=64)
    ck = str(tmp_path / "ck")
    save_sharded_index(idx, ck)
    idx2 = load_sharded_index(ck, _mesh(4), k=5)
    assert idx2.n_parts == 4
    for q in emb[:5]:
        assert idx2.search(q, "default") == idx.search(q, "default")


def test_restored_index_keeps_working(tmp_path):
    mesh = _mesh(8)
    idx, emb = _filled(mesh)
    ck = str(tmp_path / "ck")
    save_sharded_index(idx, ck)
    idx2 = load_sharded_index(ck, mesh, k=5)

    idx2.delete(["n0"])
    assert "n0" not in idx2.id_to_row
    rng = np.random.RandomState(7)
    fresh = rng.randn(3, 16).astype(np.float32)
    idx2.add(["x0", "x1", "x2"], fresh, "default")
    ids, _ = idx2.search(fresh[0], "default")
    assert ids[0] == "x0"
    idx2.decay("default", 0.01)


def test_kind_mismatch_rejected(tmp_path):
    from lazzaro_tpu.core.checkpoint import save_index
    from lazzaro_tpu.core.index import MemoryIndex

    plain = MemoryIndex(dim=8, capacity=16, edge_capacity=8)
    ck = str(tmp_path / "plain_ck")
    save_index(plain, ck)
    with pytest.raises(ValueError, match="sharded"):
        load_sharded_index(ck, _mesh(8))
    # And the plain loader still reads plain checkpoints (helper refactor).
    assert load_index(ck).capacity == 16

    # Symmetric guard: plain loader rejects sharded checkpoints loudly.
    idx, _ = _filled(_mesh(8))
    sck = str(tmp_path / "sharded_ck")
    save_sharded_index(idx, sck)
    with pytest.raises(ValueError, match="load_sharded_index"):
        load_index(sck)
