"""shard_map × Pallas top-k composition (VERDICT r3 weak #7).

``pallas_call`` has no GSPMD partitioning rule, so the blocked top-k kernel
could never run on a row-sharded arena through jit alone. Under ``shard_map``
each device sees its local rows as a plain array, so the kernel runs
per-shard (interpret mode on the CPU mesh) and only the k-candidate combine
crosses the mesh axis. These tests pin exact parity between the pallas-local
and xla-local shard scorers and the single-device oracle.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lazzaro_tpu.ops.topk import make_sharded_topk, masked_topk
from lazzaro_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    return make_mesh(("data",), (8,))


def _arena(n, d, seed=0):
    rng = np.random.default_rng(seed)
    emb = rng.standard_normal((n, d)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    mask = rng.random(n) > 0.1
    q = rng.standard_normal((4, d)).astype(np.float32)
    q /= np.linalg.norm(q, axis=1, keepdims=True)
    return emb, mask, q


def test_pallas_local_matches_xla_local_and_oracle(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    n, d = 8 * 4096, 64          # local shards are block-alignable (4096)
    emb, mask, q = _arena(n, d)
    emb_s = jax.device_put(emb, NamedSharding(mesh, P("data", None)))
    mask_s = jax.device_put(mask, NamedSharding(mesh, P("data")))

    oracle_s, oracle_i = masked_topk(jnp.asarray(emb), jnp.asarray(mask),
                                     jnp.asarray(q), 8)
    for impl in ("xla", "pallas"):
        search = make_sharded_topk(mesh, "data", k=8, impl=impl)
        s, i = search(emb_s, mask_s, jnp.asarray(q))
        np.testing.assert_array_equal(np.asarray(i), np.asarray(oracle_i),
                                      err_msg=f"rows differ for impl={impl}")
        np.testing.assert_allclose(np.asarray(s), np.asarray(oracle_s),
                                   rtol=1e-5, atol=1e-5)


def test_pallas_falls_back_when_shard_not_blockable(mesh):
    # Local rows 8*? -> 200 rows/shard: no block >= 512 divides it, so the
    # pallas request silently degrades to the XLA scorer — same answers.
    n, d = 8 * 200, 32
    emb, mask, q = _arena(n, d, seed=1)
    from jax.sharding import NamedSharding, PartitionSpec as P
    emb_s = jax.device_put(emb, NamedSharding(mesh, P("data", None)))
    mask_s = jax.device_put(mask, NamedSharding(mesh, P("data")))
    oracle_s, oracle_i = masked_topk(jnp.asarray(emb), jnp.asarray(mask),
                                     jnp.asarray(q), 5)
    search = make_sharded_topk(mesh, "data", k=5, impl="pallas")
    s, i = search(emb_s, mask_s, jnp.asarray(q))
    np.testing.assert_array_equal(np.asarray(i), np.asarray(oracle_i))
