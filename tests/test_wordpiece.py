"""WordPiece tokenizer parity vs HuggingFace ``BertTokenizer``.

Builds a synthetic ``vocab.txt`` locally (no egress) and checks that the
in-tree tokenizer reproduces HF ids exactly — basic-tokenization corner cases
included (accents, punctuation runs, CJK isolation, unknown words, long-word
bailout, padding/truncation framing).
"""

import numpy as np
import pytest

from lazzaro_tpu.models.wordpiece import WordPieceTokenizer

VOCAB = [
    "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
    "the", "quick", "brown", "fox", "jump", "##s", "##ed", "##ing",
    "over", "lazy", "dog", "un", "##want", "##able", "run", "##ner",
    "a", "i", "work", "as", "data", "engineer", "!", "?", ",", ".", "'",
    "$", "3", "##5", "cafe", "年", "中",
]

TEXTS = [
    "The quick brown fox jumps over the lazy dog",
    "unwantable running!",
    "I work as a data engineer.",
    "Café, cafe?",                      # accent stripping
    "$35!!!",                           # punctuation runs + digits
    "年中 work",                         # CJK isolation
    "supercalifragilistic",             # whole-word [UNK]
    "  whitespace\t\tand\nnewlines  ",
    "",
    "x" * 150,                          # > max_chars_per_word → [UNK]
]


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    p = tmp_path_factory.mktemp("vocab") / "vocab.txt"
    p.write_text("\n".join(VOCAB) + "\n", encoding="utf-8")
    return str(p)


@pytest.fixture(scope="module")
def hf_tok(vocab_file):
    transformers = pytest.importorskip("transformers")
    return transformers.BertTokenizer(vocab_file, do_lower_case=True)


def test_tokenize_matches_hf(vocab_file, hf_tok):
    tok = WordPieceTokenizer.from_vocab_file(vocab_file)
    for text in TEXTS:
        assert tok.tokenize(text) == hf_tok.tokenize(text), text


def test_encode_matches_hf(vocab_file, hf_tok):
    max_len = 16
    tok = WordPieceTokenizer.from_vocab_file(vocab_file, max_len=max_len)
    ours = tok.batch_encode(TEXTS)
    theirs = hf_tok(TEXTS, padding="max_length", truncation=True,
                    max_length=max_len)["input_ids"]
    assert ours == theirs


def test_special_ids_standard_layout(vocab_file):
    tok = WordPieceTokenizer.from_vocab_file(vocab_file)
    # [PAD] must be id 0: the encoder's pad mask is ``token_ids != 0``
    # (models/encoder.py pad_mask), the standard BERT vocab layout.
    assert tok.pad_id == 0 and tok.cls_id == 2 and tok.sep_id == 3
    assert tok.vocab_size == len(VOCAB)


def test_special_tokens_in_raw_text_match_hf(vocab_file, hf_tok):
    """Literal special tokens in input text pass through verbatim (HF splits
    on all_special_tokens before basic tokenization)."""
    tok = WordPieceTokenizer.from_vocab_file(vocab_file)
    for text in ["the fox [SEP] lazy dog", "[CLS] work [MASK] !", "[SEP]",
                 "a[SEP]b", "the [sep] dog"]:   # lowercase [sep] is NOT special
        assert tok.tokenize(text) == hf_tok.tokenize(text), text


def test_duplicate_vocab_lines_last_wins(tmp_path):
    """HF load_vocab assigns vocab[token]=index per line, so later duplicate
    lines win; ids must match the checkpoint's embedding rows."""
    p = tmp_path / "dup_vocab.txt"
    p.write_text("[PAD]\n[UNK]\n[CLS]\n[SEP]\ndog\ncat\ndog\n", encoding="utf-8")
    tok = WordPieceTokenizer.from_vocab_file(p)
    assert tok.vocab["dog"] == 6
    transformers = pytest.importorskip("transformers")
    hf = transformers.BertTokenizer(str(p), do_lower_case=True)
    assert tok.tokenize("dog cat") == hf.tokenize("dog cat")
    assert tok.encode("dog cat", 6) == hf(
        "dog cat", padding="max_length", truncation=True,
        max_length=6)["input_ids"]


def test_nonzero_pad_id_rejected_by_encoder(tmp_path):
    """A vocab with [PAD] off row 0 must be rejected, not silently corrupt
    the pad mask (encoder masks token id 0)."""
    from lazzaro_tpu.models.encoder import EncoderConfig, TextEncoder

    p = tmp_path / "bad_vocab.txt"
    p.write_text("[UNK]\n[PAD]\n[CLS]\n[SEP]\ndog\n", encoding="utf-8")
    tok = WordPieceTokenizer.from_vocab_file(p)
    cfg = EncoderConfig.tiny()
    with pytest.raises(ValueError, match="pad id"):
        TextEncoder(cfg, tokenizer=tok)


def test_drives_text_encoder(vocab_file):
    """WordPiece slots into TextEncoder exactly like HashTokenizer."""
    from lazzaro_tpu.models.encoder import EncoderConfig, TextEncoder

    tok = WordPieceTokenizer.from_vocab_file(vocab_file, max_len=16)
    cfg = EncoderConfig(vocab_size=tok.vocab_size, hidden=32, layers=1,
                        heads=2, mlp_dim=64, max_len=16, dtype="float32")
    enc = TextEncoder(cfg, tokenizer=tok)
    out = enc.encode_batch(["the quick fox", "lazy dog!"])
    assert out.shape == (2, 32)
    assert np.allclose(np.linalg.norm(out, axis=1), 1.0, atol=1e-5)
