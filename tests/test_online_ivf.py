"""Online IVF maintenance (ISSUE 12): cluster assignments kept by the
fused ingest dispatch itself — k-means build pauses gone.

The tentpole invariants these tests pin:

- ONE ingest dispatch per conversation with ``ivf_online`` on, single-chip
  AND on a 2-way mesh (the member append + mini-batch centroid step ride
  the dispatch that already scores the batch — jit counters prove no
  extra kernel runs);
- recall parity under churn: online-maintained tables vs a from-scratch
  offline ``build_ivf`` over the same drifted corpus, at nprobe ∈ {4, 8};
- member-pool overflow re-inserts host-side (exact-scan extras), on both
  ingest paths, with nothing ever dropped;
- ``ivf_maintenance`` is demoted to a re-seed: ingest growth alone never
  triggers it, a centroid-count change does;
- IVF × tiering: demote → serve → promote round-trips with no dense-scan
  fallback and exact scores;
- the readback-tail counters cost ZERO added dispatches.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lazzaro_tpu.core import state as S
from lazzaro_tpu.core.index import MemoryIndex
from lazzaro_tpu.ops.ivf import assignment_staleness, build_ivf
from lazzaro_tpu.serve.scheduler import RetrievalRequest
from lazzaro_tpu.utils.telemetry import Telemetry

D = 24
SEED_N = 512


def _clustered(n, n_centers=8, seed=0, spread=0.15, centers=None,
               drift=0.0):
    rng = np.random.default_rng(seed)
    if centers is None:
        centers = rng.standard_normal((n_centers, D))
        centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    if drift:
        centers = centers + drift * rng.standard_normal(centers.shape)
        centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    assign = rng.integers(0, len(centers), n)
    emb = centers[assign] + spread * rng.standard_normal((n, D))
    return emb.astype(np.float32), centers


def _seeded_index(n=SEED_N, nprobe=4, cap=2047, seed=0, online=True,
                  member_cap_factor=4, **kw):
    """Index with a seeded build over a clustered corpus (the build is
    published through the ``_ivf`` setter, which also seeds the live
    online tables)."""
    emb, centers = _clustered(n, seed=seed)
    idx = MemoryIndex(D, capacity=cap, ivf_nprobe=nprobe,
                      ivf_online=online,
                      ivf_member_cap_factor=member_cap_factor, **kw)
    ids = [f"n{i}" for i in range(n)]
    idx.add(ids, emb, [0.5] * n, [0.0] * n, ["semantic"] * n, ["s"] * n,
            "t0")
    idx._ivf = build_ivf(idx.state.emb, np.asarray(idx.state.alive),
                         member_cap_factor=member_cap_factor)
    return idx, emb, centers


def _ingest(idx, emb, tenant="t0", prefix="x", gate=0.999):
    n = len(emb)
    pending = idx.ingest_batch_dedup(emb, [0.5] * n, [1.0] * n,
                                     ["semantic"] * n, ["s"] * n, tenant,
                                     dedup_gate=gate)
    ids = [None if pending["dup"][i] else f"{prefix}{i}" for i in range(n)]
    idx.commit_ingest_dedup(pending, ids)
    return [i for i in ids if i], pending


def _recall(idx, queries, truth_ids, k=10):
    got = idx.search_batch(queries, "t0", k=k)
    hits = 0
    for (ids, _), want in zip(got, truth_ids):
        hits += len(set(ids[:k]) & set(want[:k]))
    return hits / (k * len(queries))


def _exact_truth(idx, queries, k=10):
    return [ids for ids, _ in idx.search_batch(queries, "t0", k=k,
                                               exact=True)]


# ------------------------------------------------------------- assignments
def test_online_append_routes_rows_and_keeps_residual_empty():
    """Fused-ingested rows land in member tables in-dispatch: routed
    immediately, fresh residual stays EMPTY (the pre-ISSUE-12 behavior
    grew it with every batch until the next rebuild)."""
    idx, emb, centers = _seeded_index()
    occ0 = int(idx._ivf_dev[2].sum())
    batch, _ = _clustered(32, centers=centers, seed=5)
    live, pending = _ingest(idx, batch)
    assert pending["ivf_host"] is not None
    assert len(idx._ivf_fresh) == 0
    assert int(idx._ivf_dev[2].sum()) == occ0 + len(live)
    # every appended row's recorded cluster was the argmax under the
    # centroids the dispatch scored against
    pos = np.asarray(pending["ivf_host"][1])[:, 0]
    assert (pos[np.asarray(~pending["dup"])] >= 0).all()


def test_assignment_staleness_bounded_under_mild_drift():
    """The mini-batch centroid step moves centroids a bounded amount per
    batch, so existing assignments stay near-fresh (the bench gates the
    measured fraction at ≤ 0.02; here we pin the probe itself works and
    stays small on a mildly drifting stream)."""
    idx, emb, centers = _seeded_index()
    for r in range(6):
        batch, centers = _clustered(48, centers=centers, seed=10 + r,
                                    drift=0.01)
        _ingest(idx, batch, prefix=f"r{r}_")
    dev = idx._ivf_dev
    frac = assignment_staleness(idx.state.emb, np.asarray(idx.state.alive),
                                dev[0], dev[1])
    assert 0.0 <= frac <= 0.05
    assert idx.ivf_staleness_probe() == pytest.approx(frac)


# ------------------------------------------------------------ churn parity
@pytest.mark.parametrize("nprobe", [4, 8])
def test_churn_recall_parity_vs_offline_rebuild(nprobe):
    """Drifting clustered churn: online-maintained tables must match a
    from-scratch offline build's recall@10 within the floor — the
    acceptance bar that lets the stop-the-world rebuild go."""
    idx, emb, centers = _seeded_index(nprobe=nprobe, seed=1)
    rng = np.random.default_rng(9)
    for r in range(5):
        batch, centers = _clustered(64, centers=centers, seed=20 + r,
                                    drift=0.02)
        _ingest(idx, batch, prefix=f"c{r}_")
        # delete a few old rows: churn, not just growth
        dead = [f"n{i}" for i in rng.integers(0, SEED_N, 8)]
        idx.delete(dead)

    # offline oracle: SAME final corpus, fresh offline k-means build
    oracle = MemoryIndex(D, capacity=2047, ivf_nprobe=nprobe,
                         ivf_online=False)
    ids, embs = [], []
    for nid, row in idx.id_to_row.items():
        ids.append(nid)
        embs.append(np.asarray(idx.state.emb[row], np.float32))
    embs = np.stack(embs)
    oracle.add(ids, embs, [0.5] * len(ids), [0.0] * len(ids),
               ["semantic"] * len(ids), ["s"] * len(ids), "t0")
    oracle._ivf = build_ivf(oracle.state.emb,
                            np.asarray(oracle.state.alive))

    queries, _ = _clustered(32, centers=centers, seed=77)
    truth = _exact_truth(idx, queries)
    online = _recall(idx, queries, truth)
    offline = _recall(oracle, queries, truth)
    assert online >= offline - 0.05, (online, offline)


# ---------------------------------------------------------------- overflow
def test_member_pool_overflow_reinserts_into_extras():
    """A cluster at capacity spills its appends to the exact-scan extras
    (readback position -1, host re-insert — like link-pool overflow):
    nothing is dropped, the spilled rows serve exactly."""
    idx, emb, centers = _seeded_index(member_cap_factor=1,
                                      telemetry=Telemetry(256))
    # hammer ONE cluster until its table must spill
    target = centers[0]
    batch = (np.tile(target, (96, 1))
             + 0.05 * np.random.default_rng(3).standard_normal((96, D))
             ).astype(np.float32)
    live, pending = _ingest(idx, batch)
    dup = np.asarray(pending["dup"])
    pos = np.asarray(pending["ivf_host"][1])[:len(dup), 0]
    spilled = int(((pos < 0) & ~dup).sum())
    assert spilled > 0, "fixture failed to overflow the member pool"
    assert len(idx._ivf_fresh) == spilled
    # overflow flag rode the readback; the telemetry counter saw it
    snap = idx.telemetry.snapshot()
    assert any(k.startswith("ivf.member_overflows")
               for k in snap["counters"])
    # spilled rows are served (exactly, from the extras)
    got = idx.search(batch[-1], "t0", k=10)
    assert set(got[0]) & set(live)


def test_pod_member_overflow_reinserts_into_extras():
    """Same overflow contract on the distributed ingest path."""
    from lazzaro_tpu.parallel.index import ShardedMemoryIndex
    from lazzaro_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(("data",), (2,), devices=jax.devices()[:2])
    idx = ShardedMemoryIndex(mesh, D, capacity=1023, edge_capacity=2047,
                             ivf_member_cap_factor=1)
    emb, centers = _clustered(300, seed=2)
    idx.add([f"n{i}" for i in range(300)], emb, "t0")
    assert idx.ivf_build(n_clusters=8, nprobe=4)
    target = centers[0]
    batch = (np.tile(target, (120, 1))
             + 0.05 * np.random.default_rng(4).standard_normal((120, D))
             ).astype(np.float32)
    out = idx.ingest([f"x{i}" for i in range(120)], batch, "t0",
                     dedup_gate=1.01)
    assert len(idx._ivf_fresh) > 0, "pod overflow should spill to extras"
    got = idx.search(batch[-1], "t0")
    assert set(got[0]) & set(out["created"])


# ------------------------------------------------------------ jit counters
_COUNTED = ("ingest_dedup_fused", "ingest_dedup_fused_copy", "arena_add",
            "arena_add_copy", "arena_merge_touch", "arena_merge_touch_copy",
            "edges_add", "edges_add_copy", "arena_search",
            "ivf_members_drop", "ivf_members_drop_copy")


def _count(monkeypatch):
    calls = {name: 0 for name in _COUNTED}
    for name in _COUNTED:
        orig = getattr(S, name)

        def wrapped(*a, __orig=orig, __name=name, **kw):
            calls[__name] += 1
            return __orig(*a, **kw)

        monkeypatch.setattr(S, name, wrapped)
    return calls


def test_one_dispatch_per_conversation_with_online_ivf(monkeypatch):
    """The ISSUE 12 invariant: with live online tables the whole ingest —
    dedup probe, node scatter, links, member append, centroid step — is
    STILL one dispatch; no maintenance kernel appears beside it."""
    idx, emb, centers = _seeded_index(telemetry=Telemetry(256))
    batch, _ = _clustered(16, centers=centers, seed=6)
    calls = _count(monkeypatch)
    _ingest(idx, batch)
    assert calls["ingest_dedup_fused"] == 1
    for name in _COUNTED:
        if name != "ingest_dedup_fused":
            assert calls[name] == 0, (name, calls)
    # and the readback-tail counters landed without any extra dispatch
    snap = idx.telemetry.snapshot()
    assert any(k.startswith("ivf.appends") for k in snap["counters"])
    assert any(k.startswith("ivf.member_pool_occupancy")
               for k in snap["gauges"])


def test_one_distributed_dispatch_pod_online_ivf():
    """Pod twin of the counter: one ``ingest()`` mega-batch with live
    tables costs exactly ONE distributed dispatch."""
    from lazzaro_tpu.parallel.index import ShardedMemoryIndex
    from lazzaro_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(("data",), (2,), devices=jax.devices()[:2])
    idx = ShardedMemoryIndex(mesh, D, capacity=1023, edge_capacity=255)
    emb, centers = _clustered(300, seed=8)
    idx.add([f"n{i}" for i in range(300)], emb, "t0")
    assert idx.ivf_build(n_clusters=8, nprobe=4)
    assert idx._ivf_dev is not None
    batch, _ = _clustered(24, centers=centers, seed=9)
    before = idx.ingest_dispatch_count
    idx.ingest([f"x{i}" for i in range(24)], batch, "t0", dedup_gate=0.999)
    assert idx.ingest_dispatch_count - before == 1
    # the pod serve tables are the live arrays the dispatch just updated
    tabs = idx._ivf_tables(8)
    assert tabs is not None and tabs[1] is idx._ivf_dev[1]


def test_nondedup_ingest_batch_mesh_one_distributed_dispatch():
    """ROADMAP residual closed: non-dedup ``ingest_batch`` under a mesh
    routes through the sharded factory's ``dedup=False`` program — ONE
    distributed dispatch (the GSPMD fallback re-replicated candidate
    tensors chip-to-chip); ``ingest_sharded=False`` keeps the plain-jit
    partitioning for A/B."""
    from lazzaro_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(("data",), (2,), devices=jax.devices()[:2])
    emb0, _ = _clustered(50, seed=14)

    def run(sharded):
        idx = MemoryIndex(D, capacity=1023, edge_capacity=511, mesh=mesh,
                          ingest_sharded=sharded)
        idx.add([f"n{i}" for i in range(50)], emb0, [0.5] * 50, [0.0] * 50,
                ["semantic"] * 50, ["s"] * 50, "t0")
        before = idx.ingest_dispatch_count
        batch, _ = _clustered(10, seed=15)
        rows, cands, created = idx.ingest_batch(
            [f"m{i}" for i in range(10)], batch, [0.5] * 10, [1.0] * 10,
            ["semantic"] * 10, ["s"] * 10, "t0",
            merge_ids=["n0"], merge_saliences=[0.9],
            chain_pairs=[("n0", "n1")], link_k=3)
        return idx, idx.ingest_dispatch_count - before, cands

    idx_s, n_disp, cands_s = run(True)
    assert n_disp == 1
    idx_g, _, cands_g = run(False)         # GSPMD fallback, same semantics
    for sm in cands_s:
        for nid in cands_s[sm]:
            ids_s = [c for c, _ in cands_s[sm][nid]]
            ids_g = [c for c, _ in cands_g[sm][nid]]
            assert ids_s == ids_g, (sm, nid)
    got = idx_s.search_batch(_clustered(10, seed=15)[0], "t0", k=3)
    assert all(ids for ids, _ in got)


# ------------------------------------------------------- maintenance demote
def test_ingest_growth_never_triggers_reseed_but_count_change_does():
    """Online mode: ``ivf_maintenance`` no longer rebuilds on fresh-row
    growth (appends are routed), only on a centroid-count change or
    delete churn."""
    idx, emb, centers = _seeded_index(cap=2 ** 14 - 1)
    # bypass the min-rows floor: pretend the corpus is big enough
    monkey_min = MemoryIndex._IVF_MIN_ROWS
    try:
        MemoryIndex._IVF_MIN_ROWS = 1
        batch, _ = _clustered(256, centers=centers, seed=11)
        _ingest(idx, batch)
        assert idx.ivf_maintenance() is False, \
            "routed growth must not trigger a rebuild"
        # grow until the IDEAL √N cluster count doubles the live table's
        # (build C = pow2(√512) = 32 → re-seed once √N ≥ 64, N ≥ 4096)
        more, _ = _clustered(8 * SEED_N, centers=centers, seed=12)
        for i in range(0, len(more), 512):
            _ingest(idx, more[i:i + 512], prefix=f"g{i}_")
        assert idx.ivf_maintenance() is True
        assert len(idx._ivf_fresh) == 0
    finally:
        MemoryIndex._IVF_MIN_ROWS = monkey_min


def test_offline_mode_keeps_classic_rebuild_semantics():
    """``ivf_online=False`` preserves the PR 4 behavior: fresh rows pile
    into the residual and the 25% trigger still rebuilds."""
    idx, emb, centers = _seeded_index(online=False)
    assert idx._ivf_dev is None
    batch, _ = _clustered(40, centers=centers, seed=13)
    _ingest(idx, batch)
    assert len(idx._ivf_fresh) == 40


# ------------------------------------------------------------ IVF × tiering
def test_ivf_tiering_demote_promote_round_trip(monkeypatch):
    """The PR 8 residual is gone: with a build published and rows demoted,
    serving routes the IVF×tiered program (never the dense fallback),
    cold hits rescore exactly through the bounded finish, and a
    demote→promote round trip returns to exact IVF serving."""
    idx, emb, centers = _seeded_index(n=1024, cap=4095, int8_serving=True,
                                      telemetry=Telemetry(256))
    tm = idx.enable_tiering(hot_budget_rows=600)
    cold_rows = list(range(0, 400))
    assert tm.demote_rows(cold_rows) == 400

    kw = dict(cap_take=5, max_nbr=8, super_gate=0.4, acc_boost=0.05,
              nbr_boost=0.02)
    reqs = [RetrievalRequest(query=emb[i], tenant="t0", k=10)
            for i in (0, 100, 700)]
    mode, _ = idx._serve_mode_hint(5, reqs)
    assert mode == "ivf_tiered"
    res = idx.search_fused_requests(reqs, **kw)
    for i, r in zip((0, 100, 700), res):
        assert f"n{i}" in r.ids[:3]
        assert r.scores[r.ids.index(f"n{i}")] == pytest.approx(1.0,
                                                               abs=1e-3)
    # members were scrubbed on demote: no member slot points at a cold row
    members = np.asarray(idx._ivf_dev[1])
    safe = np.maximum(members, 0)
    assert not (tm.cold_np[safe] & (members >= 0)).any()

    tm.promote_rows(cold_rows)
    assert tm.cold_count == 0
    mode2, _ = idx._serve_mode_hint(5, reqs)
    assert mode2 == "ivf"                      # pure IVF serving again
    res2 = idx.search_fused_requests(reqs, **kw)
    for i, r in zip((0, 100, 700), res2):
        assert f"n{i}" in r.ids[:3]


def test_reseed_under_tiering_excludes_cold_rows():
    """A re-seed while rows are cold must never cluster their zeroed
    master embeddings — cold rows stay covered by the residency-masked
    shadow coarse path."""
    idx, emb, centers = _seeded_index(n=1024, cap=4095, int8_serving=True)
    tm = idx.enable_tiering(hot_budget_rows=600)
    tm.demote_rows(list(range(0, 300)))
    monkey_min = MemoryIndex._IVF_MIN_ROWS
    try:
        MemoryIndex._IVF_MIN_ROWS = 1
        idx._ivf_stale = 10 ** 9               # force the re-seed branch
        assert idx.ivf_maintenance() is True
    finally:
        MemoryIndex._IVF_MIN_ROWS = monkey_min
    members = np.asarray(idx._ivf_dev[1])
    safe = np.maximum(members, 0)
    assert not (tm.cold_np[safe] & (members >= 0)).any()
