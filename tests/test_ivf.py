"""Coarse-to-fine retrieval (ops/ivf.py): recall, exactness, freshness.

The IVF stage trades HBM traffic for recall via nprobe; these tests pin:
(a) nprobe == C is EXACT (every alive row lives in one cluster or the
residual), (b) high recall on naturally clustered data at small nprobe,
(c) rows added after a build are found via the residual without rebuild,
(d) masked/dead rows never surface, (e) cluster overflow degrades to the
residual instead of dropping rows."""

import numpy as np
import jax.numpy as jnp
import pytest

from lazzaro_tpu.ops.ivf import IvfIndex, build_ivf, ivf_search


def _clustered(n_centers, per, d, seed=0, spread=0.15):
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((n_centers, d))
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    pts = np.repeat(centers, per, axis=0) + spread * rng.standard_normal(
        (n_centers * per, d))
    pts /= np.linalg.norm(pts, axis=1, keepdims=True)
    return pts.astype(np.float32), centers.astype(np.float32)


def _exact_topk(emb, mask, q, k):
    scores = q @ emb.T
    scores[:, ~mask] = -np.inf
    return np.argsort(-scores, axis=1)[:, :k]


def test_nprobe_full_is_exact():
    emb, _ = _clustered(16, 50, 32)
    mask = np.ones(len(emb), bool)
    mask[13] = False
    ivf = build_ivf(jnp.asarray(emb), mask, n_clusters=16, seed=1)
    q = emb[::97][:12]
    _, rows = ivf_search(ivf.centroids, ivf.members, ivf.residual,
                         jnp.asarray(emb), jnp.asarray(mask),
                         jnp.asarray(q), k=5, nprobe=ivf.n_clusters)
    exact = _exact_topk(emb, mask, q, 5)
    np.testing.assert_array_equal(np.sort(np.asarray(rows), axis=1),
                                  np.sort(exact, axis=1))


def test_high_recall_at_small_nprobe_on_clustered_data():
    emb, centers = _clustered(32, 120, 48, seed=2)
    mask = np.ones(len(emb), bool)
    ivf = build_ivf(jnp.asarray(emb), mask, n_clusters=32, iters=10, seed=3)
    rng = np.random.default_rng(4)
    qidx = rng.integers(0, len(emb), 64)
    q = emb[qidx]
    _, rows = ivf_search(ivf.centroids, ivf.members, ivf.residual,
                         jnp.asarray(emb), jnp.asarray(mask),
                         jnp.asarray(q), k=1, nprobe=4)
    # self-lookup: the query point itself must be found
    recall = (np.asarray(rows)[:, 0] == qidx).mean()
    assert recall >= 0.95, f"self-recall {recall}"


def test_residual_serves_fresh_rows_without_rebuild():
    emb, _ = _clustered(8, 40, 24, seed=5)
    mask = np.ones(len(emb), bool)
    ivf = build_ivf(jnp.asarray(emb), mask, n_clusters=8, seed=6)
    # a brand-new row, far from every cluster, appended post-build
    fresh = np.zeros((1, 24), np.float32)
    fresh[0, 0] = 1.0
    emb2 = np.concatenate([emb, fresh])
    mask2 = np.ones(len(emb2), bool)
    fresh_row = len(emb2) - 1
    residual = np.asarray(ivf.residual)
    residual = np.concatenate([residual[residual >= 0],
                               [fresh_row]]).astype(np.int32)
    pad = np.full((8 - len(residual) % 8 if len(residual) % 8 else 0,),
                  -1, np.int32)
    ivf2 = IvfIndex(centroids=ivf.centroids, members=ivf.members,
                    residual=jnp.asarray(np.concatenate([residual, pad])),
                    built_rows=ivf.built_rows)
    _, rows = ivf_search(ivf2.centroids, ivf2.members, ivf2.residual,
                         jnp.asarray(emb2), jnp.asarray(mask2),
                         jnp.asarray(fresh), k=1, nprobe=1)
    assert int(np.asarray(rows)[0, 0]) == fresh_row


def test_overflow_goes_to_residual_not_dropped():
    # every point in ONE tight cluster, capacity factor 1: most rows
    # overflow the single cluster's member cap but must stay findable
    emb, _ = _clustered(1, 300, 16, seed=7, spread=0.02)
    mask = np.ones(len(emb), bool)
    ivf = build_ivf(jnp.asarray(emb), mask, n_clusters=4, iters=4,
                    member_cap_factor=1, seed=8)
    total_members = int((np.asarray(ivf.members) >= 0).sum())
    total_residual = int((np.asarray(ivf.residual) >= 0).sum())
    assert total_members + total_residual == 300
    q = emb[::55][:5]
    _, rows = ivf_search(ivf.centroids, ivf.members, ivf.residual,
                         jnp.asarray(emb), jnp.asarray(mask),
                         jnp.asarray(q), k=1, nprobe=1)
    hit = (np.asarray(rows)[:, 0] == np.arange(0, 300, 55)[:5]).mean()
    assert hit == 1.0


def test_memory_index_ivf_serving_and_freshness():
    from lazzaro_tpu.core.index import MemoryIndex

    rng = np.random.default_rng(10)
    d = 32
    n = 5000                              # past _IVF_MIN_ROWS
    emb = rng.standard_normal((n, d)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    idx = MemoryIndex(dim=d, capacity=n + 64, ivf_nprobe=8)
    ids = [f"m{i}" for i in range(n)]
    for s in range(0, n, 1000):
        idx.add(ids[s:s + 1000], emb[s:s + 1000], [0.5] * 1000, [0.0] * 1000,
                ["semantic"] * 1000, ["default"] * 1000, "u1")

    # builds run ONLY via explicit maintenance (never on the query path)
    probe = rng.integers(0, n, 50)
    idx.search_batch(emb[probe[:2]], "u1", k=1)
    assert idx._ivf is None               # serving query didn't build
    assert idx.ivf_maintenance()          # background-maintenance analog
    assert idx._ivf is not None
    assert not idx.ivf_maintenance()      # fresh list empty: no rebuild

    # self-lookup recall through the coarse stage
    res = idx.search_batch(emb[probe], "u1", k=1)
    hits = sum(1 for p, (got, _) in zip(probe, res) if got == [f"m{p}"])
    assert hits >= 47, f"ivf self-recall {hits}/50"

    # a fresh post-build row must be served exactly via the residual
    fresh = np.zeros((1, d), np.float32)
    fresh[0, 5] = 1.0
    idx.add(["fresh"], fresh, [0.5], [0.0], ["semantic"], ["default"], "u1")
    assert idx._ivf_fresh                 # recorded, no rebuild yet
    (got, _), = idx.search_batch(fresh, "u1", k=1)
    assert got == ["fresh"]

    # exact=True must bypass the coarse stage entirely
    (got_exact, _), = idx.search_batch(fresh, "u1", k=1, exact=True)
    assert got_exact == ["fresh"]


def test_system_maintenance_hook_builds_ivf(tmp_path):
    """MemorySystem with ivf_serving on: once ingest passes the build
    threshold, the consolidation worker's maintenance hook builds the
    coarse index — no serving query ever pays for the k-means."""
    import json as _json

    from lazzaro_tpu.config import MemoryConfig
    from lazzaro_tpu.core.memory_system import MemorySystem

    d = 16
    per, convs = 1600, 3                   # 4800 > _IVF_MIN_ROWS after conv 3

    class Emb:
        dim = d

        def _v(self, t):
            rng = np.random.default_rng(abs(hash(t)) % (1 << 31))
            v = rng.standard_normal(d)
            return (v / np.linalg.norm(v)).tolist()

        def embed(self, t):
            return self._v(t)

        def batch_embed(self, ts):
            return [self._v(t) for t in ts]

    class LLM:
        def __init__(self):
            self.c = 0

        def completion(self, messages, response_format=None):
            base = self.c * per
            self.c += 1
            return _json.dumps({"memories": [
                {"content": f"fact {base + i} body", "type": "semantic",
                 "salience": 0.6} for i in range(per)]})

        def completion_stream(self, messages, response_format=None):
            yield self.completion(messages, response_format)

    ms = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db"),
                      verbose=False, load_from_disk=False,
                      llm_provider=LLM(), embedding_provider=Emb(),
                      max_buffer_size=20000,
                      config=MemoryConfig(journal=False, ivf_serving=4,
                                          initial_capacity=8192,
                                          auto_consolidate=False))
    for c in range(convs):
        ms.start_conversation()
        ms.add_to_short_term(f"conversation {c}", "episodic", 0.7)
        ms.end_conversation()
        if c < convs - 1:
            assert ms.index._ivf is None   # below threshold: no build yet
    assert ms.index._ivf is not None       # worker hook built it
    hits = ms.search_memories("fact 42 body")
    assert hits
    ms.close()


def test_delete_readd_churn_triggers_rebuild_and_serves_new_vector():
    """Slots reused after delete must (a) count toward the rebuild trigger
    even at stable row count, (b) be served with their NEW vector via the
    fresh residual instead of the dead vector's stale cluster, and (c)
    never surface the same node twice in one top-k (the reused row can sit
    in both a stale member slot and the residual). Advisor r4 findings."""
    from lazzaro_tpu.core.index import MemoryIndex

    rng = np.random.default_rng(11)
    d = 32
    n = 5000
    emb = rng.standard_normal((n, d)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    idx = MemoryIndex(dim=d, capacity=n + 64, ivf_nprobe=8)
    ids = [f"m{i}" for i in range(n)]
    idx.add(ids, emb, [0.5] * n, [0.0] * n, ["semantic"] * n,
            ["default"] * n, "u1")
    assert idx.ivf_maintenance()
    built = idx._ivf

    # churn: delete/re-add the same 30% of rows with NEW vectors — row
    # count is stable the whole time
    churn = [f"m{i}" for i in range(0, n, 3)]
    idx.delete(churn)
    emb2 = rng.standard_normal((len(churn), d)).astype(np.float32)
    emb2 /= np.linalg.norm(emb2, axis=1, keepdims=True)
    idx.add(churn, emb2, [0.5] * len(churn), [0.0] * len(churn),
            ["semantic"] * len(churn), ["default"] * len(churn), "u1")

    # (b) reused slots serve their NEW vector exactly (residual membership)
    res = idx.search_batch(emb2[:20], "u1", k=3)
    for want, (got, _) in zip(churn[:20], res):
        assert got and got[0] == want
        # (c) dedup: a row can never appear twice in one result list
        assert len(got) == len(set(got))

    # repeated churn of the SAME post-build rows must not grow the fresh
    # residual with duplicates (delete drops them from the fresh tuple, the
    # re-add appends exactly once)
    for _ in range(3):
        idx.delete(churn[:50])
        idx.add(churn[:50], emb2[:50], [0.5] * 50, [0.0] * 50,
                ["semantic"] * 50, ["default"] * 50, "u1")
    fresh = idx._ivf_fresh
    assert len(fresh) == len(set(fresh))

    # (a) the invalidated member slots trip the rebuild threshold
    assert idx._ivf_stale > built.built_rows // 4
    assert idx.ivf_maintenance()
    assert idx._ivf is not built          # genuinely rebuilt
    assert idx._ivf_stale == 0


def _built_index(n=5000, d=32, seed=20):
    from lazzaro_tpu.core.index import MemoryIndex

    rng = np.random.default_rng(seed)
    emb = rng.standard_normal((n, d)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    idx = MemoryIndex(dim=d, capacity=n + 64, ivf_nprobe=8)
    idx.add([f"m{i}" for i in range(n)], emb, [0.5] * n, [0.0] * n,
            ["semantic"] * n, ["default"] * n, "u1")
    assert idx.ivf_maintenance()
    return idx, emb


def test_stale_residual_cache_cross_slot_churn():
    """ADVICE r5 high: delete a FRESH row, then re-add into a DIFFERENT
    freed slot. The fresh tuple returns to its old LENGTH with different
    CONTENTS — a (build, len) cache key would serve the stale device
    residual and silently drop the live row from IVF results. The cache is
    keyed on the fresh tuple's identity, so the re-upload must happen."""
    d = 32
    idx, emb = _built_index(d=d)

    fresh_v = np.zeros((1, d), np.float32)
    fresh_v[0, 5] = 1.0
    idx.add(["f1"], fresh_v, [0.5], [0.0], ["semantic"], ["default"], "u1")
    f1_row = idx.id_to_row["f1"]
    # a search populates the device-residual cache for fresh=(f1_row,)
    (got, _), = idx.search_batch(fresh_v, "u1", k=1)
    assert got == ["f1"]
    assert idx._ivf_res_cache is not None

    # free f1's slot AND a member slot; the LIFO free list hands the
    # member slot back first, so the re-add lands in a DIFFERENT slot
    # while len(fresh) returns to exactly 1
    idx.delete(["f1", "m0"])
    fresh_v2 = np.zeros((1, d), np.float32)
    fresh_v2[0, 7] = 1.0
    idx.add(["f2"], fresh_v2, [0.5], [0.0], ["semantic"], ["default"], "u1")
    assert idx.id_to_row["f2"] != f1_row      # the cross-slot premise
    assert len(idx._ivf_fresh) == 1           # same length as the cached snapshot

    (got, _), = idx.search_batch(fresh_v2, "u1", k=1)
    assert got == ["f2"], "stale cached residual dropped the live row"


def test_ivf_setter_reconstructs_routed_bitmaps():
    """ADVICE r5 low: assigning ``idx._ivf = build`` (tests/bench compat
    surface) must rebuild the routed/in-residual bitmaps from the build —
    with them left None, every re-add of an already-routed row appends a
    duplicate to the fresh residual."""
    idx, emb = _built_index(seed=21)
    build = idx._ivf
    idx._ivf = build                          # compat assignment
    assert idx._ivf_routed is not None and idx._ivf_routed.any()
    assert idx._ivf_in_residual is not None

    # re-adding routed rows (same ids, rows already in members/residual)
    # must never grow the fresh residual
    for _ in range(3):
        idx.add(["m1", "m2"], emb[1:3], [0.5] * 2, [0.0] * 2,
                ["semantic"] * 2, ["default"] * 2, "u1")
    assert idx._ivf_fresh == []

    # a genuinely new row appends exactly once across repeated adds
    v = np.zeros((1, emb.shape[1]), np.float32)
    v[0, 3] = 1.0
    for _ in range(2):
        idx.add(["fresh1"], v, [0.5], [0.0], ["semantic"], ["default"], "u1")
    assert idx._ivf_fresh == [idx.id_to_row["fresh1"]]


def test_ivf_duplicate_rows_do_not_shorten_results():
    """ADVICE r5 low: a slot freed from a member and reused by a re-add
    sits in BOTH the stale member table and the fresh residual; host dedup
    used to shrink the result below k. Serving now over-fetches slack, so
    k distinct live rows still come back."""
    idx, emb = _built_index(seed=22)
    row = idx.id_to_row["m0"]
    idx.delete(["m0"])
    idx.add(["m0"], emb[:1], [0.5], [0.0], ["semantic"], ["default"], "u1")
    assert idx.id_to_row["m0"] == row         # LIFO reuses the same slot
    assert row in idx._ivf_fresh              # and it joined the residual

    k = 5
    (got, scores), = idx.search_batch(emb[:1], "u1", k=k)
    assert got[0] == "m0"
    assert len(got) == k, f"duplicate consumed a top-k slot: {got}"
    assert len(set(got)) == k


def test_residual_cache_keyed_on_residual_buffer_identity():
    """ISSUE 4 satellite: an ``IvfIndex`` is a mutable dataclass, so a
    same-length rebuild can swap ``ivf.residual`` in place on the SAME
    build object without passing through the ``_ivf`` setter. The device-
    residual cache is keyed on the residual buffer's identity (besides the
    build and fresh-tuple identities), so the swap must force a re-upload
    — a (build, fresh) key would keep serving the stale residual rows."""
    import jax.numpy as jnp

    idx, emb = _built_index(seed=23)
    ivf, fresh = idx._ivf_pack
    dev0 = idx._ivf_residual_dev(ivf, fresh)
    assert idx._ivf_residual_dev(ivf, fresh) is dev0   # cache hit

    new_res = np.full(np.asarray(ivf.residual).shape, -1, np.int32)
    new_res[0] = idx.id_to_row["m3"]          # same length, new content
    ivf.residual = jnp.asarray(new_res)       # in-place, setter bypassed
    dev1 = idx._ivf_residual_dev(ivf, fresh)
    assert dev1 is not dev0, "stale residual served after in-place swap"
    assert idx.id_to_row["m3"] in np.asarray(dev1).tolist()

    # the fused-serving extras cache applies the same keying
    dev2 = idx._ivf_extras_dev(ivf, fresh)
    assert idx._ivf_extras_dev(ivf, fresh) is dev2
    new_res[1] = idx.id_to_row["m4"]
    ivf.residual = jnp.asarray(new_res)
    dev3 = idx._ivf_extras_dev(ivf, fresh)
    assert dev3 is not dev2
    assert idx.id_to_row["m4"] in np.asarray(dev3).tolist()
