"""Replica-group serving (ISSUE 18): placement, routing, freshness.

The tentpole contract: G replica groups over the fleet, each a FULL pod
index on a group-local sub-mesh; every routed turn is ONE distributed
dispatch + ONE packed readback on exactly one group and is BIT-IDENTICAL
to the single-group fused result (the serving program is the same code
compiled against a narrower mesh); writes fan out through the
IngestJournal with per-group cursors so a crash anywhere in the replay
loses nothing and double-ingests nothing; overlay tenants partition
instead of replicating (tenant isolation by placement). These tests pin
each of those properties on 2- and 4-group splits of the 8-device host
mesh, plus the ReplicaRouter's per-group scheduler wiring.
"""

import numpy as np
import pytest

import jax

from lazzaro_tpu.parallel.index import ShardedMemoryIndex
from lazzaro_tpu.parallel.mesh import make_mesh, replica_group_meshes
from lazzaro_tpu.parallel.replica import ReplicaPlacement
from lazzaro_tpu.reliability import faults
from lazzaro_tpu.reliability.faults import InjectedFault
from lazzaro_tpu.serve.scheduler import ReplicaRouter, RetrievalRequest
from lazzaro_tpu.utils.telemetry import Telemetry

D = 16
CAP = 127


def _placement(n_groups, tmp_path, **kw):
    return ReplicaPlacement(
        n_groups, D, capacity=CAP, dtype=np.float32, epoch=1000.0,
        journal_path=str(tmp_path / f"journal_g{n_groups}.wal"),
        telemetry=Telemetry(), **kw)


def _corpus(n=48, seed=7):
    rng = np.random.default_rng(seed)
    return ([f"n{i}" for i in range(n)],
            rng.standard_normal((n, D)).astype(np.float32))


def _reqs(emb, tenant="shared", nq=6, k=5):
    return [RetrievalRequest(query=emb[i], tenant=tenant, k=k)
            for i in range(nq)]


def _assert_bit_identical(a, b):
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        assert ra.ids == rb.ids
        np.testing.assert_array_equal(np.asarray(ra.scores, np.float32),
                                      np.asarray(rb.scores, np.float32))


# ----------------------------------------------------------------- meshes
def test_replica_group_meshes_partition_the_fleet():
    meshes = replica_group_meshes(4)
    assert len(meshes) == 4
    seen = []
    for m in meshes:
        assert m.shape["data"] == len(jax.devices()) // 4
        seen.extend(m.devices.ravel().tolist())
    assert sorted(d.id for d in seen) == [d.id for d in jax.devices()]
    with pytest.raises(ValueError):
        replica_group_meshes(3)     # 3 does not divide 8


# ----------------------------------------------------------------- parity
@pytest.mark.parametrize("n_groups", [2, 4])
def test_routed_turn_bit_parity_with_single_group(n_groups, tmp_path):
    """A routed turn served by one replica group is bit-identical to the
    same corpus served by a standalone single-group index on a mesh of
    the group's size — and every group agrees with every other."""
    ids, emb = _corpus()
    pl = _placement(n_groups, tmp_path)
    pl.ingest(ids, emb, "shared")
    per = len(jax.devices()) // n_groups
    solo = ShardedMemoryIndex(
        make_mesh(("data",), (per,), devices=jax.devices()[:per]),
        dim=D, capacity=CAP, dtype=np.float32, epoch=1000.0,
        telemetry=Telemetry())
    solo.ingest(ids, emb, "shared")
    reqs = _reqs(emb)
    want = solo.serve_requests(reqs)
    got = pl.serve(reqs)
    _assert_bit_identical(got, want)
    for g in pl.groups:                      # replicas agree bitwise too
        _assert_bit_identical(g.serve_requests(reqs), want)


# --------------------------------------------------------------- affinity
def test_tenant_affinity_isolation(tmp_path):
    """An overlay tenant's rows exist ONLY on its home group: no other
    group ever holds (or can serve) them, while shared-tier facts
    replicate everywhere."""
    ids, emb = _corpus(32)
    pl = _placement(4, tmp_path)
    pl.ingest(ids, emb, "shared")
    rng = np.random.default_rng(11)
    ov_emb = rng.standard_normal((8, D)).astype(np.float32)
    pl.ingest([f"ov{i}" for i in range(8)], ov_emb, "agent-a", overlay=True)
    home = pl.group_for_tenant("agent-a")
    for g, idx in enumerate(pl.groups):
        ov_here = [i for i in idx.id_to_row if i.startswith("ov")]
        shared_here = [i for i in idx.id_to_row if i.startswith("n")]
        assert len(shared_here) == len(ids)          # shared: replicated
        assert len(ov_here) == (8 if g == home else 0)
    # affine routing: every overlay batch lands on the home group
    reqs = _reqs(ov_emb, tenant="agent-a", nq=4, k=3)
    assert pl.route_batch(reqs) == home
    res = pl.serve(reqs)
    assert res[0].ids[0] == "ov0"
    # a mixed batch with overlay requests still pins to the home group
    mixed = reqs[:2] + _reqs(emb, nq=2)
    assert pl.route_batch(mixed) == home


def test_shared_reads_spread_least_loaded(tmp_path):
    ids, emb = _corpus(24)
    pl = _placement(2, tmp_path)
    pl.ingest(ids, emb, "shared")
    for _ in range(4):
        pl.serve(_reqs(emb, nq=2))
    assert pl._turns == [2, 2]      # idle fleet spreads round-robin


# ---------------------------------------------------------------- journal
def test_crash_mid_replay_loses_nothing_and_doubles_nothing(tmp_path):
    """The crash-during-replay fault cell: an injected death between two
    subscriber replays leaves some groups behind — catch_up() replays
    the journal past each cursor and converges with ZERO lost facts and
    ZERO double-ingests (the id filter + in-dispatch dedup probe)."""
    ids, emb = _corpus(20)
    pl = _placement(4, tmp_path)
    pl.ingest(ids[:8], emb[:8], "shared")        # healthy baseline batch
    with faults.INJECTOR.armed("replica.mid_replay", times=1):
        with pytest.raises(InjectedFault):
            pl.ingest(ids[8:], emb[8:], "shared")
    assert faults.INJECTOR.fired("replica.mid_replay") >= 1
    assert pl.lag() >= 1                         # someone is behind
    behind = [g for g, idx in enumerate(pl.groups)
              if len(idx.id_to_row) < len(ids)]
    assert behind                                # the crash was real
    pl.catch_up()
    for idx in pl.groups:
        assert sorted(idx.id_to_row) == sorted(ids)      # zero lost
        assert len(idx.row_to_id) == len(ids)            # zero doubled
    assert pl.lag() == 0 and pl.staleness() == 0.0
    assert pl.journal.pending_count == 0         # commit retired the drain
    # replicas converged to the same serving answers as the primary
    reqs = _reqs(emb, nq=4)
    base = pl.groups[0].serve_requests(reqs)
    for g in pl.groups[1:]:
        _assert_bit_identical(g.serve_requests(reqs), base)


def test_replay_is_idempotent_when_repeated(tmp_path):
    """Replaying an already-applied journal batch is a no-op: cursors
    reset to 0 (the fresh-process state) must not double-ingest."""
    ids, emb = _corpus(12)
    pl = _placement(2, tmp_path)
    pl.ingest(ids, emb, "shared")
    before = [dict(idx.id_to_row) for idx in pl.groups]
    pl.ingest(ids[:0], emb[:0], "shared")        # no-op write
    pl._applied = [0, 0]                         # model a restarted process
    pl.catch_up()                                # journal already committed
    for idx, snap in zip(pl.groups, before):
        assert idx.id_to_row == snap


# --------------------------------------------------------------- dispatch
def test_one_dispatch_per_routed_turn_with_telemetry_on(tmp_path):
    """Telemetry fully on, a routed turn costs exactly ONE device
    dispatch fleet-wide: the serving program runs group-local and no
    other group is touched."""
    ids, emb = _corpus(32)
    pl = _placement(2, tmp_path)
    pl.ingest(ids, emb, "shared")
    reqs = _reqs(emb)
    for g in pl.groups:
        g.serve_requests(reqs)                   # warm/compile both groups
    calls = {g: 0 for g in range(pl.n_groups)}
    for g, idx in enumerate(pl.groups):
        orig = idx._dispatch

        def counting(fn, *a, _g=g, _orig=orig, **kw):
            calls[_g] += 1
            return _orig(fn, *a, **kw)

        idx._dispatch = counting
    res = pl.serve(reqs)
    assert len(res) == len(reqs)
    assert sum(calls.values()) == 1


# ----------------------------------------------------- review regressions
def _tenants_with_distinct_homes(pl):
    """Two tenant names whose stable home groups differ."""
    by_home = {}
    for i in range(64):
        t = f"tenant-{i}"
        by_home.setdefault(pl.group_for_tenant(t), t)
        if len(by_home) == pl.n_groups:
            break
    homes = sorted(by_home)
    return by_home[homes[0]], by_home[homes[1]]


def test_deferred_fanout_interleaved_homes_loses_nothing(tmp_path):
    """Cursor contiguity: interleaved ``replicate=False`` ingests from
    tenants with DIFFERENT home groups must not let any group's cursor
    jump past a seq it never applied — the later replicate() has to
    deliver every batch to every group (and only then may commit retire
    it from the journal)."""
    pl = _placement(2, tmp_path)
    ta, tb = _tenants_with_distinct_homes(pl)
    rng = np.random.default_rng(3)
    emb_a = rng.standard_normal((6, D)).astype(np.float32)
    emb_b = rng.standard_normal((6, D)).astype(np.float32)
    ids_a = [f"a{i}" for i in range(6)]
    ids_b = [f"b{i}" for i in range(6)]
    pl.ingest(ids_a, emb_a, ta, replicate=False)     # seq 1, home A
    pl.ingest(ids_b, emb_b, tb, replicate=False)     # seq 2, home B
    pl.replicate()
    for idx in pl.groups:
        assert sorted(idx.id_to_row) == sorted(ids_a + ids_b)  # zero lost
        assert len(idx.row_to_id) == 12                        # zero doubled
    assert pl.journal.pending_count == 0
    assert pl.lag() == 0


def test_home_group_is_process_stable():
    """Home-group assignment must survive restarts (PYTHONHASHSEED):
    both the placement and the router derive it from CRC32, never the
    salted builtin ``hash``."""
    import zlib

    from lazzaro_tpu.utils.hashing import tenant_home_group

    for tenant in ("agent-a", "agent-b", "shared", "tenant-42"):
        want = (zlib.crc32(tenant.encode("utf-8")) & 0xFFFFFFFF) % 4
        assert tenant_home_group(tenant, 4) == want


def test_overlay_registration_survives_restart(tmp_path):
    """A previously-overlay tenant stays partitioned and pinned after a
    new process reopens the same journal — registration is durable past
    commit/compaction, not in-memory only."""
    ids, emb = _corpus(16)
    pl = _placement(2, tmp_path)
    pl.ingest(ids, emb, "shared")
    rng = np.random.default_rng(9)
    ov1 = rng.standard_normal((4, D)).astype(np.float32)
    pl.ingest([f"ov{i}" for i in range(4)], ov1, "agent-c", overlay=True)
    home = pl.group_for_tenant("agent-c")
    assert pl.journal.pending_count == 0     # committed (and compacted)

    pl2 = _placement(2, tmp_path)            # new process, same journal
    assert "agent-c" in pl2.overlay_tenants
    assert pl2.group_for_tenant("agent-c") == home
    ov2 = rng.standard_normal((4, D)).astype(np.float32)
    pl2.ingest([f"ow{i}" for i in range(4)], ov2, "agent-c")  # no flag
    for g, idx in enumerate(pl2.groups):
        here = [i for i in idx.id_to_row if i.startswith("ow")]
        assert len(here) == (4 if g == home else 0)


def test_ingest_result_merges_counters(tmp_path):
    """ReplicaPlacement.ingest() surfaces the fused ingest's counter
    deltas instead of always returning an empty dict."""
    ids, emb = _corpus(8)
    pl = _placement(2, tmp_path)
    out = pl.ingest(ids, emb, "shared")
    assert out["counters"]
    assert "dedup_hits" in out["counters"]
    # new ids, identical content: the in-dispatch dedup probe fires and
    # the delta must surface through the merged result
    dup = pl.ingest([f"dup{i}" for i in range(4)], emb[:4], "shared")
    assert dup["counters"].get("dedup_hits", 0) >= 1


# ----------------------------------------------------------------- router
def test_replica_router_per_group_schedulers(tmp_path):
    """ReplicaRouter: overlay tenants pin to their home group's
    scheduler, shared traffic spreads least-loaded, and each group keeps
    its OWN breaker/admission state."""
    ids, emb = _corpus(24)
    pl = _placement(2, tmp_path)
    pl.ingest(ids, emb, "shared")
    rng = np.random.default_rng(5)
    ov_emb = rng.standard_normal((4, D)).astype(np.float32)
    pl.ingest([f"ov{i}" for i in range(4)], ov_emb, "agent-b", overlay=True)
    router = pl.make_router(max_batch=8)
    try:
        home = router.group_for_tenant("agent-b")
        assert home == pl.group_for_tenant("agent-b")
        futs = router.submit_many(
            _reqs(ov_emb, tenant="agent-b", nq=3, k=3) + _reqs(emb, nq=3))
        results = [f.result(timeout=30) for f in futs]
        assert results[0].ids[0] == "ov0"
        assert all(r.ids for r in results)
        st = router.stats()
        assert st["n_groups"] == 2
        assert sum(g["requests_served"] for g in st["groups"]) == 6
        # the overlay sub-group landed on the home scheduler
        assert st["groups"][home]["requests_served"] >= 3
        # per-group breakers are independent objects
        breakers = {id(s.breaker) for s in router.schedulers}
        assert len(breakers) == 2
    finally:
        router.close()
