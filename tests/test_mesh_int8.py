"""Int8 serving composed with the device mesh (VERDICT r4 next #7).

The quantized shadow is per-row state, so it row-shards exactly like the
master arena; each device scans its local int8 rows and only the
k-candidate combine crosses the mesh axis. These tests run on the
8-device CPU mesh (conftest) and check the sharded int8 scan against
both the single-device int8 oracle (must be bit-identical: same
quantization, same dot products, different partitioning) and the exact
bf16 scan (rank-parity within quantization error).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from lazzaro_tpu.parallel.mesh import make_mesh


def _corpus(n, d, seed=0):
    rng = np.random.default_rng(seed)
    emb = rng.standard_normal((n, d)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    return emb


def test_sharded_int8_matches_single_device_int8():
    from lazzaro_tpu.ops.quant import quantize_rows, quantized_topk
    from lazzaro_tpu.ops.topk import make_sharded_int8_topk, shard_matrix, shard_rows

    n, d, k = 4096, 64, 10
    mesh = make_mesh(("data",), (8,))
    emb = _corpus(n, d)
    mask = np.ones((n,), bool)
    mask[::7] = False                     # realistic holes
    queries = _corpus(12, d, seed=1)

    q8, scale = quantize_rows(jnp.asarray(emb))
    s_ref, r_ref = quantized_topk(q8, scale, jnp.asarray(mask),
                                  jnp.asarray(queries), k)

    import jax
    q8_sh = jax.device_put(q8, shard_matrix(mesh))
    scale_sh = jax.device_put(scale, shard_rows(mesh))
    mask_sh = jax.device_put(jnp.asarray(mask), shard_rows(mesh))
    search = make_sharded_int8_topk(mesh, "data", k=k)
    s_got, r_got = search(q8_sh, scale_sh, mask_sh, jnp.asarray(queries))

    np.testing.assert_array_equal(np.asarray(r_got), np.asarray(r_ref))
    np.testing.assert_allclose(np.asarray(s_got), np.asarray(s_ref),
                               rtol=0, atol=1e-6)


def test_memory_index_mesh_int8_rank_parity():
    """MemoryIndex(mesh=..., int8_serving=True): the serving scan routes
    through the sharded int8 path and agrees with the exact path on
    well-separated data; exact=True bypasses the shadow."""
    from lazzaro_tpu.core.index import MemoryIndex

    n, d = 2000, 48
    mesh = make_mesh(("data",), (8,))
    emb = _corpus(n, d, seed=3)
    idx = MemoryIndex(dim=d, capacity=n + 64, mesh=mesh, int8_serving=True)
    assert idx.int8_serving               # no longer clamped under a mesh
    ids = [f"m{i}" for i in range(n)]
    idx.add(ids, emb, [0.5] * n, [0.0] * n, ["semantic"] * n,
            ["default"] * n, "u1")

    probe = np.arange(0, n, 97)
    res = idx.search_batch(emb[probe], "u1", k=3)
    for p, (got, scores) in zip(probe, res):
        assert got[0] == f"m{p}"          # self-hit survives quantization
        assert scores[0] > 0.98
    assert idx._int8_shadow is not None   # the shadow actually served

    # mutation invalidates; the next search re-quantizes and sees the row
    new = _corpus(1, d, seed=9)
    idx.add(["fresh"], new, [0.5], [0.0], ["semantic"], ["default"], "u1")
    (got, _), = idx.search_batch(new, "u1", k=1)
    assert got == ["fresh"]

    # exact=True must serve from the bf16 master, not the shadow
    (got_exact, s_exact), = idx.search_batch(emb[probe[:1]], "u1", k=1,
                                             exact=True)
    assert got_exact == [f"m{probe[0]}"]
    assert abs(s_exact[0] - 1.0) < 5e-3


def test_system_mesh_int8_end_to_end(tmp_path):
    """MemorySystem on a mesh with int8_serving: chat → consolidate →
    search works and serves through the sharded int8 scan."""
    from lazzaro_tpu.config import MemoryConfig
    from lazzaro_tpu.core.memory_system import MemorySystem

    mesh = make_mesh(("data",), (8,))
    ms = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db"),
                      verbose=False, load_from_disk=False, mesh=mesh,
                      config=MemoryConfig(journal=False, int8_serving=True))
    assert ms.index.int8_serving
    ms.start_conversation()
    ms.chat("I work as a data engineer on a big ETL project.")
    ms.chat("I love hiking in the mountains on weekends.")
    ms.end_conversation()
    hits = ms.search_memories("what is the user's job?")
    # the hashing embedder's scores for short texts sit close together, so
    # int8 rounding may legitimately reorder near-ties — require presence,
    # not rank
    assert hits and any("data engineer" in n.content for n in hits)
    assert ms.index._int8_shadow is not None
    ms.close()
