"""Tiered memory (ISSUE 8): HBM hot set + host cold tier.

Acceptance pins:
- mixed hot/cold serving is IDENTICAL to the all-hot fused path on the
  same fixture — bit-identical scores against the quant path (the tiered
  rescore is the same gathered-row einsum), same ids/ranking/gate
  verdicts in every mode, and bit-identical boost columns (salience /
  access_count / last_accessed) — across exact, quant, and IVF modes and
  a 2-way mesh;
- hot-only turns cost exactly ONE dispatch; a turn whose candidate window
  touches cold rows costs exactly TWO (coarse scan + bounded finish);
- checkpoint round-trip carries the residency column and cold-store
  contents, and the reloaded index serves bit-identically;
- the pump: watermark-driven demotion, hysteresis after promotion,
  access-driven promotion at the hit threshold, write/delete hooks.
"""

import tempfile

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lazzaro_tpu.core import state as S
from lazzaro_tpu.core.index import MemoryIndex
from lazzaro_tpu.serve.scheduler import RetrievalRequest
from lazzaro_tpu.tier import ColdStore, TierManager, TierPump

D = 32
KW = dict(cap_take=5, max_nbr=8, super_gate=0.4, acc_boost=0.05,
          nbr_boost=0.02, now=1234.5)


def _vecs(n, seed, base_axis=None, spread=0.5):
    r = np.random.default_rng(seed)
    nz = r.standard_normal((n, D)).astype(np.float32)
    if base_axis is None:
        return nz / np.linalg.norm(nz, axis=1, keepdims=True)
    nz *= spread / np.linalg.norm(nz, axis=1, keepdims=True)
    base = np.zeros(D, np.float32)
    base[base_axis] = 1.0
    v = base[None, :] + nz
    return v / np.linalg.norm(v, axis=1, keepdims=True)


def _fill(idx, n=200, seed=0, edges=True, supers=False):
    emb = _vecs(n, seed)
    ids = [f"n{i}" for i in range(n)]
    sup = [supers and i % 29 == 0 for i in range(n)]
    idx.add(ids, emb, [0.5] * n, [0.0] * n, ["semantic"] * n,
            ["default"] * n, "u0", is_super=sup)
    if edges:
        idx.add_edges([(f"n{i}", f"n{i + 1}", 0.7) for i in range(n - 1)],
                      "u0")
    return emb


def _reqs(emb, nq=8, k=10, boost=True, seed=9):
    r = np.random.default_rng(seed)
    q = emb[:nq] + 0.01 * r.standard_normal((nq, D)).astype(np.float32)
    return [RetrievalRequest(query=q[i], tenant="u0", k=k,
                             gate_enabled=True, boost=boost)
            for i in range(nq)]


def _assert_results_equal(a_list, b_list, bitwise_scores=True):
    for a, b in zip(a_list, b_list):
        assert a.ids == b.ids
        if bitwise_scores:
            assert a.scores == b.scores
        else:
            assert np.allclose(a.scores, b.scores, atol=2e-6)
        assert a.fast == b.fast
        assert a.gate_id == b.gate_id


def _assert_boost_columns_equal(ia, ib):
    for col in ("salience", "access_count", "last_accessed"):
        assert np.array_equal(np.asarray(getattr(ia.state, col)),
                              np.asarray(getattr(ib.state, col))), col


# --------------------------------------------------------------- cold store
def test_cold_store_roundtrip_and_growth():
    import ml_dtypes

    cs = ColdStore(D, dtype=ml_dtypes.bfloat16, initial_slots=4)
    v = _vecs(40, 1).astype(ml_dtypes.bfloat16)
    rows = list(range(5, 45))
    cs.put(rows, v, np.ones((40, D), np.int8),
           np.arange(40, dtype=np.float32))
    assert len(cs) == 40                   # grew past 4 initial slots
    got = cs.gather([7, 5, 44])
    assert got.dtype == np.dtype(ml_dtypes.bfloat16)
    assert got.view(np.uint16).tolist() == \
        v[[2, 0, 39]].view(np.uint16).tolist()   # bit-exact round trip
    cs.drop([7])
    assert 7 not in cs and len(cs) == 39
    r, codes, scales = cs.snapshot_codes()
    assert len(r) == 39 and codes.shape == (39, D)


def test_cold_store_memmap(tmp_path):
    cs = ColdStore(D, dtype=np.float32, path=str(tmp_path / "cold.bin"),
                   initial_slots=4)
    v = _vecs(10, 2)
    cs.put(list(range(10)), v, np.zeros((10, D), np.int8),
           np.zeros(10, np.float32))
    assert np.array_equal(cs.gather([3])[0], v[3])
    cs.put([99], v[:1], np.zeros((1, D), np.int8),
           np.zeros(1, np.float32))       # grows the mapped file
    assert np.array_equal(cs.gather([99])[0], v[0])


# --------------------------------------------------- demote / promote cycle
def test_demote_promote_restores_exact_bytes():
    idx = MemoryIndex(dim=D, capacity=255, dtype=jnp.bfloat16,
                      int8_serving=True)
    _fill(idx, edges=False)
    before = np.asarray(idx.state.emb).copy()
    tm = idx.enable_tiering(hot_budget_rows=64, hysteresis_s=0.0)
    cold = [idx.id_to_row[f"n{i}"] for i in range(100, 200)]
    assert tm.demote_rows(cold) == 100
    emb = np.asarray(idx.state.emb)
    assert not emb[cold].any()             # master surrendered
    assert tm.cold_count == 100
    assert tm.promote_rows(cold) == 100
    after = np.asarray(idx.state.emb)
    # every REAL row round-trips bit-exact (the sentinel scratch row is
    # fair game for the padded scatters, like every other kernel)
    cap = idx.state.capacity
    assert np.array_equal(before[:cap].view(np.uint16),
                          after[:cap].view(np.uint16))
    assert tm.cold_count == 0


def test_super_rows_are_pinned_hot():
    idx = MemoryIndex(dim=D, capacity=255, int8_serving=True)
    _fill(idx, supers=True)
    tm = idx.enable_tiering(hot_budget_rows=16, hysteresis_s=0.0)
    tm.run_once(now=1.0)
    sup_rows = np.asarray(sorted(idx._super_rows))
    assert not tm.cold_np[sup_rows].any()


# ----------------------------------------------------------- serving parity
def _pair(int8, tiering_on, ivf=0, mesh=None, slack=512, supers=True):
    # pinned epoch: the parity asserts compare boost columns BITWISE, and
    # with the fixed now=1234.5 a wall-clock epoch makes last_accessed
    # = now - epoch ≈ -1.8e9 — bit-equal only while both ctors' epochs
    # round into the same 128-second f32 bucket (a phase-of-the-suite
    # flake)
    idx = MemoryIndex(dim=D, capacity=255, int8_serving=int8,
                      coarse_slack=slack, ivf_nprobe=ivf, mesh=mesh,
                      epoch=1000.0)
    emb = _fill(idx, supers=supers)
    if tiering_on:
        tm = idx.enable_tiering(hot_budget_rows=64, hysteresis_s=0.0)
        tm.demote_rows([idx.id_to_row[f"n{i}"] for i in range(100, 200)])
        assert tm.cold_count > 90          # supers among them stay hot
    return idx, emb


def test_parity_quant_mode_bitwise():
    """Mixed hot/cold vs all-hot QUANT fused: the tiered rescore is the
    same gathered-row einsum, so scores are bit-identical — and so are
    the boost columns the two serves scatter."""
    idx_t, emb = _pair(int8=True, tiering_on=True)
    idx_h, _ = _pair(int8=True, tiering_on=False)
    r_t = idx_t.search_fused_requests(_reqs(emb), **KW)
    r_h = idx_h.search_fused_requests(_reqs(emb), **KW)
    assert any(r.cold_hits > 0 for r in r_t)   # the fixture IS mixed
    _assert_results_equal(r_t, r_h, bitwise_scores=True)
    _assert_boost_columns_equal(idx_t, idx_h)


def test_parity_exact_mode():
    """Mixed hot/cold vs all-hot EXACT fused: same ids/ranking/gate and
    boost columns; scores agree to f32 round-off (the exact kernel scores
    via one whole-arena matmul, the tiered path via the gathered-row
    einsum — different contraction shapes, same math)."""
    idx_t, emb = _pair(int8=False, tiering_on=True)
    idx_h, _ = _pair(int8=False, tiering_on=False)
    r_t = idx_t.search_fused_requests(_reqs(emb), **KW)
    r_h = idx_h.search_fused_requests(_reqs(emb), **KW)
    _assert_results_equal(r_t, r_h, bitwise_scores=False)
    _assert_boost_columns_equal(idx_t, idx_h)


def test_parity_ivf_mode():
    """Mixed hot/cold vs the all-hot fused IVF path at full probe width
    (nprobe == n_clusters ⇒ the IVF candidate set is the whole arena):
    tiering bypasses the centroid prefilter — it is the one structure
    that still covers demoted rows — and must return the same results."""
    n = 4500                               # above the IVF build minimum
    idx_t = MemoryIndex(dim=D, capacity=5000, int8_serving=True,
                        coarse_slack=5001, ivf_nprobe=4096)
    idx_h = MemoryIndex(dim=D, capacity=5000, int8_serving=True,
                        coarse_slack=5001, ivf_nprobe=4096)
    emb = _vecs(n, 0)
    ids = [f"n{i}" for i in range(n)]
    for i_ in (idx_t, idx_h):
        i_.add(ids, emb, [0.5] * n, [0.0] * n, ["semantic"] * n,
               ["default"] * n, "u0")
        i_.add_edges([(f"n{j}", f"n{j + 1}", 0.7) for j in range(200)],
                     "u0")
        assert i_.ivf_maintenance(iters=2)
    tm = idx_t.enable_tiering(hot_budget_rows=1024, hysteresis_s=0.0)
    tm.demote_rows([idx_t.id_to_row[f"n{i}"] for i in range(2000, 4500)])
    reqs = _reqs(emb, nq=4)
    r_t = idx_t.search_fused_requests(reqs, **KW)
    r_h = idx_h.search_fused_requests(reqs, **KW)
    _assert_results_equal(r_t, r_h, bitwise_scores=False)
    _assert_boost_columns_equal(idx_t, idx_h)


@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs 2 devices")
def test_parity_mesh_2way_bitwise():
    from lazzaro_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(("data",), (2,), devices=jax.devices()[:2])
    idx_t, emb = _pair(int8=True, tiering_on=True, mesh=mesh)
    idx_h, _ = _pair(int8=True, tiering_on=False, mesh=mesh)
    r_t = idx_t.search_fused_requests(_reqs(emb), **KW)
    r_h = idx_h.search_fused_requests(_reqs(emb), **KW)
    assert any(r.cold_hits > 0 for r in r_t)
    _assert_results_equal(r_t, r_h, bitwise_scores=True)
    _assert_boost_columns_equal(idx_t, idx_h)


@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs 2 devices")
def test_sharded_index_per_shard_cold_stores():
    from lazzaro_tpu.parallel.index import ShardedMemoryIndex
    from lazzaro_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(("data",), (2,), devices=jax.devices()[:2])

    def build():
        si = ShardedMemoryIndex(mesh, dim=D, capacity=255,
                                int8_serving=True, coarse_slack=256,
                                cap_take=5, max_nbr=8)
        emb = _vecs(200, 0)
        # tenant affinity packs a tenant's rows into its home partition;
        # 200 rows overflow one 128-row partition, so the corpus — and
        # the demoted slab — genuinely spans both shards
        si.add([f"n{i}" for i in range(200)], emb, "u0")
        si.add_edges([(f"n{i}", f"n{i + 1}", 0.7) for i in range(199)])
        return si, emb

    si_t, emb = build()
    si_h, _ = build()
    tm = si_t.attach_tiering(hot_budget_rows=64, hysteresis_s=0.0)
    tm.demote_rows([si_t.id_to_row[f"n{i}"] for i in range(60, 200)])
    assert sum(len(s) for s in tm.stores) == 140
    assert all(len(s) > 0 for s in tm.stores)    # BOTH shards hold rows
    reqs = _reqs(emb, nq=6, k=8)
    r_t = si_t.serve_requests(reqs)
    r_h = si_h.serve_requests(reqs)
    for a, b in zip(r_t, r_h):
        assert a.ids == b.ids and a.scores == b.scores
    assert np.array_equal(np.asarray(si_t.state.salience),
                          np.asarray(si_h.state.salience))


def test_dense_demote_never_surfaces_in_exact_search():
    """Residency parity (ISSUE 18): a DENSE-layout demote zero-fills the
    master row but leaves it alive, so the plain exact scan used to
    surface demoted rows as a score-0.0 top-k tail (the paged layout
    frees the slot, so the two layouts diverged). With the cold column
    masked to -inf, demote is indistinguishable from delete on the
    exact serve — bitwise, full k-list — on one chip and a 2-way mesh."""
    from lazzaro_tpu.parallel.mesh import make_mesh

    meshes = [None]
    if len(jax.devices()) >= 2:
        meshes.append(make_mesh(("data",), (2,), devices=jax.devices()[:2]))
    demoted = sorted(f"n{i}" for i in range(100, 200))
    for mesh in meshes:
        idx_d = MemoryIndex(dim=D, capacity=255, mesh=mesh, epoch=1000.0)
        emb = _fill(idx_d, edges=False)
        tm = idx_d.enable_tiering(hot_budget_rows=64, hysteresis_s=0.0)
        assert tm.demote_rows([idx_d.id_to_row[i] for i in demoted]) == 100
        idx_x = MemoryIndex(dim=D, capacity=255, mesh=mesh, epoch=1000.0)
        _fill(idx_x, edges=False)
        idx_x.delete(demoted)
        for q in emb[100:106]:      # queries aimed AT the demoted slab
            ids_d, sc_d = idx_d.search(q, "u0", k=20)
            ids_x, sc_x = idx_x.search(q, "u0", k=20)
            assert not (set(ids_d) & set(demoted))
            assert ids_d == ids_x
            assert sc_d == sc_x     # bitwise: same masked score vector


# --------------------------------------------------------- dispatch counts
def _count_tier_dispatches(monkeypatch):
    calls = {"scan": 0, "finish": 0}
    for name in ("search_fused_tiered", "search_fused_tiered_copy",
                 "search_fused_tiered_read", "search_fused_tiered_ragged",
                 "search_fused_tiered_ragged_copy",
                 "search_fused_tiered_ragged_read"):
        orig = getattr(S, name)

        def w(*a, __o=orig, **k):
            calls["scan"] += 1
            return __o(*a, **k)

        monkeypatch.setattr(S, name, w)
    for name in ("tier_cold_finish", "tier_cold_finish_copy",
                 "tier_cold_rescore"):
        orig = getattr(S, name)

        def w2(*a, __o=orig, **k):
            calls["finish"] += 1
            return __o(*a, **k)

        monkeypatch.setattr(S, name, w2)
    return calls


def test_hot_only_turn_is_one_dispatch_cold_turn_two(monkeypatch):
    """The tiered serving contract: a turn whose coarse candidate window
    is all-hot stays ONE dispatch + ONE readback; a cold-hit turn pays
    exactly ONE bounded finish dispatch more."""
    idx = MemoryIndex(dim=D, capacity=511, int8_serving=True,
                      serve_k_max=16)
    n_hot, n_cold = 120, 280
    hot = _vecs(n_hot, 1, base_axis=0)
    cold = _vecs(n_cold, 2, base_axis=1)
    emb = np.concatenate([hot, cold])
    ids = [f"n{i}" for i in range(n_hot + n_cold)]
    idx.add(ids, emb, [0.5] * len(ids), [0.0] * len(ids),
            ["semantic"] * len(ids), ["default"] * len(ids), "u0")
    idx.add_edges([(f"n{i}", f"n{i + 1}", 0.7) for i in range(50)], "u0")
    tm = idx.enable_tiering(hot_budget_rows=128, hysteresis_s=0.0)
    tm.demote_rows([idx.id_to_row[f"n{i}"]
                    for i in range(n_hot, n_hot + n_cold)])

    hot_q = _vecs(4, 3, base_axis=0)
    cold_q = _vecs(4, 4, base_axis=1)
    mk = lambda q: [RetrievalRequest(query=q[i], tenant="u0", k=8,  # noqa: E731
                                     gate_enabled=True, boost=True)
                    for i in range(len(q))]
    idx.search_fused_requests(mk(hot_q), **KW)     # warm
    idx.search_fused_requests(mk(cold_q), **KW)
    calls = _count_tier_dispatches(monkeypatch)

    res = idx.search_fused_requests(mk(hot_q), **KW)
    assert calls == {"scan": 1, "finish": 0}       # ONE dispatch, all hot
    assert all(r.cold_hits == 0 for r in res)

    calls["scan"] = calls["finish"] = 0
    res = idx.search_fused_requests(mk(cold_q), **KW)
    assert calls == {"scan": 1, "finish": 1}       # exactly TWO
    assert any(r.cold_hits > 0 for r in res)
    assert tm.cold_turns >= 4
    assert 0.0 < (tm.cold_turns / tm.turns) <= 1.0


# ------------------------------------------------------------------ pump
def test_pump_watermarks_hysteresis_and_promotion():
    idx = MemoryIndex(dim=D, capacity=255, int8_serving=True)
    n = 200
    emb = _vecs(n, 0)
    ids = [f"n{i}" for i in range(n)]
    sal = [0.9 if i < 50 else 0.1 for i in range(n)]
    idx.add(ids, emb, sal, [0.0] * n, ["semantic"] * n, ["default"] * n,
            "u0")
    tm = idx.enable_tiering(hot_budget_rows=100, high_watermark=0.9,
                            low_watermark=0.75, promote_hits=2,
                            hysteresis_s=1000.0)
    # 200 hot > 0.9 * 100 → demote down to 75 hot, coldest-first
    out = tm.run_once(now=0.0)
    assert out["demoted"] == 125
    assert tm.hot_rows == 75
    hot_rows = [idx.id_to_row[f"n{i}"] for i in range(50)]
    assert not tm.cold_np[np.asarray(hot_rows)].any()   # high-sal survived

    # access-driven promotion: below the hit threshold nothing queues
    cold_row = int(np.flatnonzero(tm.cold_np)[0])
    tm.note_cold_hits([cold_row])
    assert cold_row not in tm._promote_queue
    tm.note_cold_hits([cold_row])
    assert cold_row in tm._promote_queue
    out = tm.run_once(now=1.0)
    assert out["promoted"] == 1 and not tm.cold_np[cold_row]
    # hysteresis: the promoted row is demotion-immune inside the window
    cand = tm.select_demotion_candidates(200, now=2.0)
    assert cold_row not in cand
    # ... and demotable again after it expires
    cand = tm.select_demotion_candidates(200, now=5000.0)
    assert cold_row in cand


def test_pump_thread_and_per_pass_cap():
    idx = MemoryIndex(dim=D, capacity=255, int8_serving=True)
    _fill(idx, edges=False)
    tm = idx.enable_tiering(hot_budget_rows=64, high_watermark=1.0,
                            low_watermark=1.0, hysteresis_s=0.0)
    tm.max_demote_per_pass = 50
    out = tm.run_once(now=0.0)
    assert out["demoted"] == 50            # the cap spreads the drain
    pump = TierPump(tm, interval_s=0.01).start()
    try:
        import time as _t
        deadline = _t.time() + 20.0
        while tm.hot_rows > 64 and _t.time() < deadline:
            _t.sleep(0.02)
    finally:
        pump.stop()
    assert tm.hot_rows == 64


def test_write_and_delete_hooks_clear_residency():
    idx = MemoryIndex(dim=D, capacity=255, int8_serving=True)
    emb = _fill(idx, edges=False)
    tm = idx.enable_tiering(hot_budget_rows=64, hysteresis_s=0.0)
    r_cold = idx.id_to_row["n150"]
    tm.demote_rows([r_cold, idx.id_to_row["n151"]])
    assert tm.cold_np[r_cold]
    # re-add writes a fresh embedding → the cold residue must drop
    idx.add(["n150"], emb[150:151], [0.5], [0.0], ["semantic"],
            ["default"], "u0")
    assert not tm.cold_np[r_cold] and r_cold not in tm.stores[0]
    # delete frees the row AND its cold-store slot
    r151 = idx.id_to_row["n151"]
    idx.delete(["n151"])
    assert not tm.cold_np[r151] and tm.cold_count == 0
    # a freed-then-reused row starts hot
    idx.add(["fresh"], emb[0:1], [0.5], [0.0], ["semantic"], ["default"],
            "u0")
    assert not tm.cold_np[idx.id_to_row["fresh"]]


def test_get_embedding_serves_cold_rows_from_store():
    idx = MemoryIndex(dim=D, capacity=255, int8_serving=True)
    emb = _fill(idx, edges=False)
    stored = np.asarray(idx.state.emb[idx.id_to_row["n7"]], np.float32)
    tm = idx.enable_tiering(hot_budget_rows=64)
    tm.demote_rows([idx.id_to_row["n7"]])
    got = idx.get_embedding("n7")
    assert np.array_equal(got, stored)


# ------------------------------------------------------------- checkpoint
def test_checkpoint_roundtrip_mixed_hot_cold_bit_identical():
    """Save/load carries the residency column + cold-store contents, and
    the reloaded index serves BIT-IDENTICAL results on a mixed fixture."""
    from lazzaro_tpu.core.checkpoint import load_index, save_index

    idx = MemoryIndex(dim=D, capacity=255, dtype=jnp.bfloat16,
                      int8_serving=True, coarse_slack=256)
    emb = _fill(idx, supers=True)
    tm = idx.enable_tiering(hot_budget_rows=64, hysteresis_s=0.0,
                            high_watermark=0.8, low_watermark=0.5)
    tm.demote_rows([idx.id_to_row[f"n{i}"] for i in range(100, 200)])
    reqs = _reqs(emb, boost=False)
    before = idx.search_fused_requests(reqs, **KW)
    with tempfile.TemporaryDirectory() as tmp:
        save_index(idx, tmp)
        back = load_index(tmp)
    assert back.tiering is not None
    assert back.tiering.cold_count == tm.cold_count
    assert np.array_equal(back.tiering.cold_np, tm.cold_np)
    assert back.tiering.high_watermark == 0.8       # policy knobs survive
    after = back.search_fused_requests(reqs, **KW)
    _assert_results_equal(before, after, bitwise_scores=True)
    # cold-store payload is byte-identical
    a = tm.stores[0].snapshot_all()
    b = back.tiering.stores[0].snapshot_all()
    oa, ob = np.argsort(a[0]), np.argsort(b[0])
    assert np.array_equal(a[0][oa], b[0][ob])
    assert np.array_equal(a[1][oa], b[1][ob])
    assert np.array_equal(a[2][oa], b[2][ob])


def test_shadow_rebuild_patches_cold_codes():
    """A full shadow rebuild quantizes from the master — which holds ZEROS
    for cold rows. The cold store's codes must be patched back, or the
    coarse scan silently stops covering the cold tier."""
    idx = MemoryIndex(dim=D, capacity=255, int8_serving=True,
                      coarse_slack=256)
    emb = _fill(idx)
    tm = idx.enable_tiering(hot_budget_rows=64)
    tm.demote_rows([idx.id_to_row[f"n{i}"] for i in range(100, 200)])
    r = idx.search_fused_requests(_reqs(emb, nq=4, boost=False), **KW)
    idx._int8_dirty = True                 # force a full rebuild
    r2 = idx.search_fused_requests(_reqs(emb, nq=4, boost=False), **KW)
    _assert_results_equal(r, r2, bitwise_scores=True)
    # and a cold row is still findable at all
    q = np.asarray(tm.gather_cold([idx.id_to_row["n150"]])[0], np.float32)
    got = idx.search_fused_requests(
        [RetrievalRequest(query=q, tenant="u0", k=3)], **KW)[0]
    assert got.ids and got.ids[0] == "n150"
