"""Binary arena checkpoint: round-trip fidelity + scale timing."""

import os
import time

import jax.numpy as jnp
import numpy as np
import pytest

from lazzaro_tpu.core.checkpoint import load_index, save_index
from lazzaro_tpu.core.index import MemoryIndex


def _fill(index, n, tenant="default", seed=0):
    rng = np.random.RandomState(seed)
    ids = [f"node_{i}" for i in range(n)]
    emb = rng.randn(n, index.dim).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    index.add(ids, emb, [0.5] * n, [1000.0 + i for i in range(n)],
              ["semantic"] * n, ["work"] * n, tenant)
    return ids, emb


def test_round_trip_search_identical(tmp_path):
    idx = MemoryIndex(dim=32, capacity=64, edge_capacity=32)
    ids, emb = _fill(idx, 20)
    idx.add_edges([("node_0", "node_1", 0.7), ("node_1", "node_2", 0.4)],
                  "default")
    ck = str(tmp_path / "ckpt")
    save_index(idx, ck)
    idx2 = load_index(ck)

    assert len(idx2) == len(idx)
    assert idx2.id_to_row == idx.id_to_row
    assert idx2.edge_slots == idx.edge_slots
    assert idx2.epoch == idx.epoch
    for q in emb[:5]:
        a = idx.search(q, "default", k=5)
        b = idx2.search(q, "default", k=5)
        assert a[0] == b[0]
        np.testing.assert_allclose(a[1], b[1], rtol=1e-6)


def test_round_trip_then_mutate(tmp_path):
    """The restored index must keep working: adds, deletes, edges, decay."""
    idx = MemoryIndex(dim=16, capacity=32, edge_capacity=16)
    _fill(idx, 10)
    ck = str(tmp_path / "ckpt")
    save_index(idx, ck)
    idx2 = load_index(ck)

    idx2.delete(["node_3"])
    assert "node_3" not in idx2.id_to_row
    rng = np.random.RandomState(1)
    more = rng.randn(40, 16).astype(np.float32)   # forces arena growth
    idx2.add([f"new_{i}" for i in range(40)], more, [0.5] * 40,
             [2000.0] * 40, ["episodic"] * 40, ["personal"] * 40, "default")
    assert len(idx2) == 49
    idx2.add_edges([("new_0", "new_1", 0.9)], "default")
    idx2.decay("default", 0.01)
    ids, _ = idx2.search(more[0], "default", k=3)
    assert ids[0] == "new_0"


def test_round_trip_bfloat16(tmp_path):
    idx = MemoryIndex(dim=16, capacity=32, edge_capacity=8, dtype=jnp.bfloat16)
    _, emb = _fill(idx, 8)
    ck = str(tmp_path / "ck")
    save_index(idx, ck)
    idx2 = load_index(ck)
    assert idx2.state.emb.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(idx.state.emb).view(np.uint16),
        np.asarray(idx2.state.emb).view(np.uint16))   # bit-exact
    a = idx.search(emb[0], "default", k=3)
    b = idx2.search(emb[0], "default", k=3)
    assert a[0] == b[0]


def test_multi_tenant_membership_restored(tmp_path):
    idx = MemoryIndex(dim=8, capacity=64, edge_capacity=8)
    _fill(idx, 5, tenant="alice", seed=1)
    rng = np.random.RandomState(2)
    emb = rng.randn(3, 8).astype(np.float32)
    idx.add(["b_0", "b_1", "b_2"], emb, [0.5] * 3, [0.0] * 3,
            ["semantic"] * 3, ["work"] * 3, "bob")
    ck = str(tmp_path / "ck")
    save_index(idx, ck)
    idx2 = load_index(ck)
    assert idx2.tenant_nodes["alice"] == idx.tenant_nodes["alice"]
    assert idx2.tenant_nodes["bob"] == {"b_0", "b_1", "b_2"}
    ids, _ = idx2.search(emb[0], "bob", k=2)
    assert ids[0] == "b_0"
    ids_a, _ = idx2.search(emb[0], "alice", k=2)
    assert "b_0" not in ids_a


def test_overwrite_existing_checkpoint(tmp_path):
    idx = MemoryIndex(dim=8, capacity=16, edge_capacity=8)
    _fill(idx, 4)
    ck = str(tmp_path / "ck")
    save_index(idx, ck)
    idx.delete(["node_0"])
    save_index(idx, ck)                    # overwrite path
    idx2 = load_index(ck)
    assert "node_0" not in idx2.id_to_row
    assert len(idx2) == 3
    vdirs = [e for e in os.listdir(ck) if e.startswith("v")]
    assert len(vdirs) == 1                 # superseded version pruned


def test_crash_between_payload_and_pointer_keeps_old_snapshot(tmp_path):
    """A version dir that landed without the CURRENT flip (the crash window)
    must be invisible to readers and cleaned by the next save."""
    idx = MemoryIndex(dim=8, capacity=16, edge_capacity=8)
    _fill(idx, 4)
    ck = str(tmp_path / "ck")
    save_index(idx, ck)

    # Simulate the crash: stage a bogus v2 payload, never flip CURRENT.
    os.makedirs(os.path.join(ck, "v2"))
    (tmp_path / "ck" / "v2" / "meta.json").write_text("{corrupt")

    idx2 = load_index(ck)                  # still reads v1
    assert len(idx2) == 4

    idx.delete(["node_1"])
    save_index(idx, ck)                    # next save supersedes + prunes v2
    idx3 = load_index(ck)
    assert len(idx3) == 3
    assert not os.path.isdir(os.path.join(ck, "v2"))


def test_load_missing_checkpoint_raises(tmp_path):
    import pytest as _pytest
    with _pytest.raises(FileNotFoundError):
        load_index(str(tmp_path / "nope"))


def test_scale_timing_vs_row_store(tmp_path):
    """50k × 256 snapshot must be far faster than row-wise parquet of the
    same data (the motivation for this module; at 1M the gap is minutes)."""
    n, d = 50_000, 256
    idx = MemoryIndex(dim=d, capacity=n, edge_capacity=8)
    rng = np.random.RandomState(0)
    emb = rng.randn(n, d).astype(np.float32)
    ids = [f"n{i}" for i in range(n)]
    idx.add(ids, emb, [0.5] * n, [0.0] * n, ["semantic"] * n,
            ["work"] * n, "default")

    t0 = time.perf_counter()
    save_index(idx, str(tmp_path / "ck"))
    t_save = time.perf_counter() - t0
    t0 = time.perf_counter()
    idx2 = load_index(str(tmp_path / "ck"))
    t_load = time.perf_counter() - t0
    assert len(idx2) == n

    from lazzaro_tpu.core.store import ArrowStore
    store = ArrowStore(str(tmp_path / "db"))
    rows = [{"id": i, "content": "", "embedding": e}
            for i, e in zip(ids, emb.tolist())]
    t0 = time.perf_counter()
    store.add_nodes(rows)
    t_store = time.perf_counter() - t0

    # Guard against regressing to per-row Python. The typical gap is
    # 10-50×; asserting only < 1× keeps the test robust to CI noise
    # (GC pauses, cold page cache) while still catching a real regression.
    assert t_save < t_store, (t_save, t_store)
    assert t_load < t_store, (t_load, t_store)


def test_nonzero_rank_never_touches_filesystem(tmp_path, monkeypatch):
    """Multi-host: only process 0 writes (advisor r1: checkpoint.py:63).
    Simulated by patching process_count/index — a rank-1 save must leave the
    checkpoint dir untouched."""
    import jax
    from lazzaro_tpu.core import checkpoint as C

    idx = MemoryIndex(dim=16, capacity=32, edge_capacity=16)
    _fill(idx, 8)
    ck = tmp_path / "ck"
    monkeypatch.setattr(C, "_ckpt_barrier", lambda: None)   # no real pod here
    monkeypatch.setattr(C, "_broadcast_ok", lambda ok: True)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    C.save_index(idx, str(ck))
    assert not ck.exists()
    # When rank 0 reports failure, ranks != 0 must raise too instead of
    # silently returning success (advisor r3: checkpoint.py:84).
    monkeypatch.setattr(C, "_broadcast_ok", lambda ok: False)
    with pytest.raises(RuntimeError, match="failed on process 0"):
        C.save_index(idx, str(ck))
    monkeypatch.setattr(C, "_broadcast_ok", lambda ok: True)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    C.save_index(idx, str(ck))
    assert (ck / "CURRENT").exists()


def test_payload_fsynced_before_pointer_flip(tmp_path, monkeypatch):
    """Durability: the staged npz/meta and their directories are fsynced
    before CURRENT flips (advisor r1: checkpoint.py:77)."""
    import os
    from lazzaro_tpu.core import checkpoint as C

    synced = []
    real_fsync = os.fsync
    monkeypatch.setattr(os, "fsync", lambda fd: (synced.append(fd), real_fsync(fd)))
    idx = MemoryIndex(dim=16, capacity=32, edge_capacity=16)
    _fill(idx, 8)
    C.save_index(idx, str(tmp_path / "ck"))
    # meta.json + arrays.npz + staged dir + ckpt dir (x2) + CURRENT >= 5
    assert len(synced) >= 5


def test_round_trip_restores_super_row_tracking(tmp_path):
    """ISSUE 4: ``load_index`` bypasses ``add``, so the super-row set the
    fused IVF serving kernel's extras rely on must be rebuilt from the
    restored ``is_super`` column."""
    idx = MemoryIndex(dim=16, capacity=64, edge_capacity=32)
    rng = np.random.RandomState(1)
    emb = rng.randn(6, 16).astype(np.float32)
    idx.add([f"n{i}" for i in range(6)], emb, [0.5] * 6, [0.0] * 6,
            ["semantic"] * 6, ["work"] * 6, "default",
            is_super=[False, True, False, True, False, False])
    ck = str(tmp_path / "ckpt")
    save_index(idx, ck)
    idx2 = load_index(ck)
    assert idx2._super_rows == idx._super_rows
    assert idx2._super_rows_frozen == idx._super_rows_frozen
    assert idx2._super_rows == {idx.id_to_row["n1"], idx.id_to_row["n3"]}
