"""Persistence + cross-instance sync (reference test_v03_migration.py pattern,
SURVEY §4(e)): two MemorySystem instances sharing one store dir — save in A,
version-poll + reload in B. This is the framework's "multi-node without a real
cluster" approximation; the real multi-chip path is tested via the mesh tests."""

import pytest

from lazzaro_tpu import MemorySystem

from tests.fakes import MockEmbedder, MockLLM, extraction_response

FACT = {"content": "User plays the violin", "type": "semantic",
        "salience": 0.8, "topic": "personal"}


def make_ms(tmp_db, load=False, **kw):
    llm = MockLLM(sniffers={
        "Extract distinct, atomic facts": extraction_response([FACT]),
    })
    defaults = dict(enable_async=False, auto_consolidate=False,
                    load_from_disk=load, db_dir=tmp_db,
                    llm_provider=llm, embedding_provider=MockEmbedder(),
                    verbose=False)
    defaults.update(kw)
    return MemorySystem(**defaults)


def ingest_one(ms):
    ms.start_conversation()
    ms.add_to_short_term("I play violin", "episodic", 0.7)
    ms.end_conversation()


def test_save_restart_reload(tmp_db):
    a = make_ms(tmp_db)
    ingest_one(a)
    assert a.buffer.size()[0] == 1
    a.close()

    b = make_ms(tmp_db, load=True)
    assert b.buffer.size()[0] == 1
    node = b.buffer.get_node("node_1")
    assert node.content == FACT["content"]
    assert node.shard_key == "personal"
    # node_counter restored from max node_N id
    assert b.node_counter == 1
    # the reloaded node is searchable through the arena
    results = b.search_memories("User plays the violin")
    assert [n.id for n in results] == ["node_1"]
    b.close()


def test_cross_instance_version_sync(tmp_db):
    a = make_ms(tmp_db)
    b = make_ms(tmp_db, load=True)
    assert b.buffer.size()[0] == 0
    assert b.check_for_updates() is False  # nothing new yet

    ingest_one(a)  # A writes; store version bumps

    assert b.check_for_updates() is True
    assert b.buffer.size()[0] == 1
    assert b.buffer.get_node("node_1").content == FACT["content"]
    a.close()
    b.close()


def test_switch_user_isolates_graphs(tmp_db):
    ms = make_ms(tmp_db)
    ingest_one(ms)
    assert ms.buffer.size()[0] == 1

    ms.switch_user("bob")
    assert ms.user_id == "bob"
    assert ms.buffer.size()[0] == 0
    assert ms.search_memories("violin") == []

    ms.switch_user("default")
    assert ms.buffer.size()[0] == 1
    assert [n.id for n in ms.search_memories("User plays the violin")] == ["node_1"]
    ms.close()


def test_save_load_state_json(tmp_db, tmp_path):
    ms = make_ms(tmp_db)
    ingest_one(ms)
    path = str(tmp_path / "snapshot.json")
    assert "saved" in ms.save_state(path)

    ms2 = make_ms(str(tmp_path / "db2"))
    assert "loaded" in ms2.load_state(path)
    assert ms2.buffer.size()[0] == 1
    assert ms2.node_counter == 1
    # arena rebuilt: search works after snapshot load
    assert [n.id for n in ms2.search_memories("User plays the violin")] == ["node_1"]
    ms.close()
    ms2.close()


def test_eviction_deletes_from_store(tmp_db):
    facts = [{"content": f"User fact number {i} about topic {i}",
              "type": "semantic", "salience": 0.5, "topic": "personal"}
             for i in range(6)]
    llm = MockLLM(sniffers={
        "Extract distinct, atomic facts": extraction_response(facts)})
    ms = MemorySystem(enable_async=False, auto_consolidate=False,
                      load_from_disk=False, db_dir=tmp_db, max_buffer_size=3,
                      llm_provider=llm,
                      embedding_provider=MockEmbedder(dim=16),
                      verbose=False)
    ms.start_conversation()
    ms.add_to_short_term("many facts", "episodic", 0.7)
    ms.end_conversation()

    nodes, _ = ms.buffer.size()
    assert nodes == 3  # evicted down to the buffer limit
    stored = ms.store.get_nodes(user_id="default")
    assert len(stored) == 3
    ms.close()
