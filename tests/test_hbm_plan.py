"""Memory-safe serving: the admission-time HBM planner (ISSUE 11).

Covers the cost model (analytic over-bounding, calibration growth,
persistence), the split decision tree (fused → chunked scan → planned
batch split → typed infeasible), and the split-parity contract: a
planner-forced 2-way and 4-way batch split and a chunked-scan dispatch
all return BIT-IDENTICAL top-k, gate verdicts, and boost columns vs the
single-dispatch kernel; under-budget geometries still cost exactly ONE
dispatch (jit-counter pinned); infeasible geometries shed typed at the
scheduler, never hang; warmups skip what admission would refuse; the
ingest mega-batch splits planned.
"""

import numpy as np
import pytest

from lazzaro_tpu.core import state as S
from lazzaro_tpu.core.index import MemoryIndex
from lazzaro_tpu.plan import (CostModel, Geometry, HbmPlanner,
                              PlanDecision, plan_geometry)
from lazzaro_tpu.reliability import DeviceOom, PlanInfeasible
from lazzaro_tpu.reliability.faults import INJECTOR, oom_error
from lazzaro_tpu.serve.scheduler import (QueryScheduler, RetrievalRequest,
                                         RetrievalResult)
from lazzaro_tpu.utils.telemetry import Telemetry

D = 32
EPOCH = 1000.0
KW = dict(cap_take=5, max_nbr=8, super_gate=0.4, acc_boost=0.05,
          nbr_boost=0.02, now=1234.5)

_ARENA_COLS = ("emb", "salience", "timestamp", "last_accessed",
               "access_count", "type_id", "shard_id", "tenant_id", "alive",
               "is_super")


@pytest.fixture(autouse=True)
def _clean_faults():
    INJECTOR.clear()
    yield
    INJECTOR.clear()


def _vecs(n, seed):
    r = np.random.default_rng(seed)
    v = r.standard_normal((n, D)).astype(np.float32)
    return v / np.linalg.norm(v, axis=1, keepdims=True)


def _build_multitenant(n_tenants=4, per=32, **extra):
    """Disjoint per-tenant row sets: a contiguous split of tenant-major
    queries can never boost one row from two sub-dispatches, so the
    boost columns of a split turn are bit-identical to the fused one."""
    n = n_tenants * per
    idx = MemoryIndex(dim=D, capacity=255, epoch=EPOCH,
                      telemetry=Telemetry(), **extra)
    emb = _vecs(n, 0)
    for t in range(n_tenants):
        ids = [f"t{t}n{i}" for i in range(per)]
        idx.add(ids, emb[t * per:(t + 1) * per], [0.5] * per, [0.0] * per,
                ["semantic"] * per, ["default"] * per, f"u{t}",
                is_super=[i % 13 == 0 for i in range(per)])
        idx.add_edges([(f"t{t}n{i}", f"t{t}n{i + 1}", 0.7)
                       for i in range(per - 1)], f"u{t}", now=EPOCH)
    return idx, emb


def _mt_reqs(emb, n_tenants=4, per=32, per_tenant=2, k=10, boost=True):
    """Tenant-major query order: a q-way contiguous split (q ≤ tenants)
    keeps every tenant's queries inside one sub-dispatch."""
    out = []
    r = np.random.default_rng(7)
    for t in range(n_tenants):
        for j in range(per_tenant):
            q = emb[t * per + j] + 0.01 * r.standard_normal(D).astype(
                np.float32)
            out.append(RetrievalRequest(query=q, tenant=f"u{t}", k=k,
                                        gate_enabled=True, boost=boost))
    return out


def _assert_results_equal(a_list, b_list):
    for a, b in zip(a_list, b_list):
        assert a.ids == b.ids
        assert a.scores == b.scores            # bit-identical, not close
        assert a.fast == b.fast
        assert a.gate_id == b.gate_id


def _assert_state_bitwise(ia, ib):
    for col in _ARENA_COLS:
        a = np.asarray(getattr(ia.state, col))
        b = np.asarray(getattr(ib.state, col))
        assert np.array_equal(a, b), f"arena.{col} diverged"


# =====================================================================
# cost model
# =====================================================================
def test_predict_monotonic_in_batch_rows_and_mesh():
    m = CostModel()
    g = Geometry(batch=32, rows=1 << 16, dim=256, k=64)
    assert m.predict(g.with_(batch=64)) > m.predict(g)
    assert m.predict(g.with_(rows=1 << 17)) > m.predict(g)
    assert m.predict(g.with_(mesh_parts=4)) < m.predict(g)
    assert m.predict(g.with_(scan_chunk=8)) < m.predict(g)
    assert m.resident_bytes(g) < m.predict(g)


def test_observe_grows_multiplier_until_sound(tmp_path):
    m = CostModel()
    g = Geometry(batch=16, rows=4096, dim=128, k=32)
    base = m.predict(g)
    assert m.observe(g, base * 0.5)                # already over-bounded
    assert not m.observe(g, base * 3.0)            # beat the bound → grow
    assert m.predict(g) >= base * 3.0              # now over-bounds it
    assert m.residuals                             # residual log recorded
    path = str(tmp_path / "calib.json")
    m.save(path)
    m2 = CostModel.load(path)
    assert m2.predict(g) == m.predict(g)
    assert m2.residuals == {k: pytest.approx(v, abs=0)
                            for k, v in m.residuals.items()} or \
        m2.residuals.keys() == m.residuals.keys()


def test_decision_tree_rungs():
    m = CostModel()
    g = Geometry(mode="exact", batch=64, rows=1 << 15, dim=256, k=64,
                 mesh_parts=1)
    full = m.predict(g)
    # 1. fits → fused
    d = plan_geometry(m, g, int(full / 0.9) + 1)
    assert d.fused and d.splits == 1 and d.scan_chunk == 0
    # 2. budget between chunked and unchunked → scan chunked, ONE dispatch
    chunked = m.predict(g.with_(scan_chunk=8))
    d = plan_geometry(m, g, int((full + chunked) / 2 / 0.9))
    assert d.feasible and d.splits == 1 and d.scan_chunk > 0
    # 3. below even the maximally chunked prediction → batch split
    sub = m.predict(g.with_(batch=8, scan_chunk=8))
    d = plan_geometry(m, g, int(sub / 0.9) + 1)
    assert d.feasible and d.splits > 1
    # 4. below the resident floor → typed infeasible
    d = plan_geometry(m, g, int(m.resident_bytes(g) * 0.5))
    assert not d.feasible


def test_planner_disabled_and_oom_learning():
    p = HbmPlanner(budget_bytes=0)
    assert not p.active
    assert p.plan(Geometry()).fused
    g = Geometry(batch=64, rows=1 << 14, dim=128, k=64)
    p2 = HbmPlanner(budget_bytes=1 << 30)
    d = p2.plan(g)
    assert d.fused
    before = p2.model.predict(g)
    p2.note_oom(g)                      # the model under-estimated
    assert p2.model.predict(g) > before
    harder = p2.replan_after_oom(g, d)
    assert harder is not None and harder.splits >= 2


# =====================================================================
# split parity: planner-forced 2-way / 4-way vs the single dispatch
# =====================================================================
@pytest.mark.parametrize("splits", [2, 4])
def test_planned_batch_split_bit_parity(splits):
    """A planner-forced batch split returns bit-identical top-k, gate
    verdicts, AND boost columns vs the single-dispatch kernel (disjoint
    per-tenant row sets: no cross-sub-dispatch float reassociation)."""
    idx_c, emb = _build_multitenant()
    idx_s, _ = _build_multitenant()
    reqs = _mt_reqs(emb)
    r_c = idx_c.search_fused_requests(list(reqs), **KW)
    geom = idx_s._serve_geometry(len(reqs), "exact", idx_s.serve_k_max)
    forced = PlanDecision(True, splits, 0, 0, 0, "test-forced")
    r_s = idx_s._serve_planned(list(reqs), geom, forced,
                               dict(KW), replanned=False)
    _assert_results_equal(r_c, r_s)
    _assert_state_bitwise(idx_c, idx_s)
    assert idx_s.telemetry.counter_total("plan.split_dispatches") == splits


def test_scan_chunked_dispatch_bit_parity_and_one_dispatch(monkeypatch):
    """The cheapest degradation rung: a planner-chunked arena scan stays
    ONE dispatch (jit-counter pinned) and is bit-identical — only the
    streaming tile width changes."""
    calls = {"n": 0}
    orig = S.search_fused_ragged

    def wrapped(*a, **kw):
        calls["n"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(S, "search_fused_ragged", wrapped)
    idx_c, emb = _build_multitenant()
    idx_s, _ = _build_multitenant()
    reqs = _mt_reqs(emb)
    r_c = idx_c.search_fused_requests(list(reqs), **KW)
    geom = idx_s._serve_geometry(len(reqs), "exact", idx_s.serve_k_max)
    forced = PlanDecision(True, 1, 4, 0, 0, "test-chunked")
    before = calls["n"]
    r_s = idx_s._serve_planned(list(reqs), geom, forced,
                               dict(KW), replanned=False)
    assert calls["n"] == before + 1                # still ONE dispatch
    _assert_results_equal(r_c, r_s)
    _assert_state_bitwise(idx_c, idx_s)
    assert idx_s.telemetry.counter_total("plan.scan_chunked") == 1


def test_under_budget_geometry_still_one_dispatch(monkeypatch):
    """Planner ACTIVE with a generous budget: the admitted fused path
    costs exactly ONE donated dispatch — planning adds arithmetic, never
    dispatches."""
    counted = ("search_fused_ragged", "search_fused_ragged_copy",
               "search_fused_ragged_read")
    calls = {name: 0 for name in counted}
    for name in counted:
        orig = getattr(S, name)

        def wrapped(*a, __orig=orig, __name=name, **kw):
            calls[__name] += 1
            return __orig(*a, **kw)

        monkeypatch.setattr(S, name, wrapped)
    idx, emb = _build_multitenant(hbm_budget_bytes=1 << 34)
    idx.search_fused_requests(_mt_reqs(emb), **KW)
    assert calls["search_fused_ragged"] == 1
    assert calls["search_fused_ragged_copy"] == 0
    assert calls["search_fused_ragged_read"] == 0
    assert idx.telemetry.counter_total("plan.split_dispatches") == 0


def test_throttled_budget_splits_with_bit_parity():
    """End-to-end through the real admission path: a budget sized
    between the one-bucket and full-batch predictions forces a planned
    split whose results are bit-identical."""
    idx_c, emb = _build_multitenant()
    reqs = _mt_reqs(emb, per_tenant=8, boost=False)
    r_c = idx_c.search_fused_requests(list(reqs), **KW)
    m = idx_c.planner.model
    g = idx_c._serve_geometry(len(reqs), "exact", idx_c.serve_k_max)
    budget = int(m.predict(g.with_(batch=8, scan_chunk=8)) / 0.9) + 4096
    idx_s, _ = _build_multitenant(hbm_budget_bytes=budget)
    r_s = idx_s.search_fused_requests(list(reqs), **KW)
    _assert_results_equal(r_c, r_s)
    assert idx_s.telemetry.counter_total("plan.split_dispatches") >= 2
    assert idx_s.telemetry.counter_total("plan.planned_turns") == 1


# =====================================================================
# typed rejection: PlanInfeasible at every admission surface
# =====================================================================
def test_infeasible_geometry_raises_typed():
    idx, emb = _build_multitenant(hbm_budget_bytes=4096)  # < resident set
    with pytest.raises(PlanInfeasible):
        idx.search_fused_requests(_mt_reqs(emb), **KW)
    assert idx.telemetry.counter_total("plan.infeasible") >= 1
    with pytest.raises(PlanInfeasible):
        idx.ingest_batch_dedup(_vecs(8, 3), [0.5] * 8, [EPOCH] * 8,
                               ["semantic"] * 8, ["default"] * 8,
                               "u0", dedup_gate=0.95)


def test_scheduler_admission_sheds_infeasible_typed():
    """The scheduler admission path (ISSUE 11): an infeasible geometry
    fails the futures with the typed PlanInfeasible at submit — shed
    like LoadShed, the queue and the device never see it."""
    def never(reqs):                   # executor must never run
        raise AssertionError("admitted an infeasible request")

    def check(reqs):
        raise PlanInfeasible("no split fits")

    tel = Telemetry()
    sched = QueryScheduler(never, telemetry=tel, admission_check=check)
    futs = sched.submit_many([RetrievalRequest(
        query=np.zeros(D, np.float32), tenant="t") for _ in range(3)])
    for f in futs:
        with pytest.raises(PlanInfeasible):
            f.result(timeout=30)       # typed, immediate, never a hang
    sched.close()
    assert sched.requests_shed == 3
    assert tel.counter_total("plan.infeasible_shed") == 3


def test_scheduler_executor_planinfeasible_demuxes():
    """Backstop: PlanInfeasible raised mid-batch by the executor demuxes
    to every future of the batch like any typed error."""
    def ex(reqs):
        raise PlanInfeasible("grew past the budget after admission")

    sched = QueryScheduler(ex, telemetry=Telemetry())
    f = sched.submit(RetrievalRequest(query=np.zeros(D, np.float32),
                                      tenant="t"))
    with pytest.raises(PlanInfeasible):
        f.result(timeout=30)
    sched.close()


def test_warmup_skips_infeasible_geometries():
    idx, _ = _build_multitenant(hbm_budget_bytes=4096)
    out = idx.warmup_serving(geometries=(8,))
    assert out == {}                   # skipped typed, not crashed
    assert idx.telemetry.counter_total("plan.warmup_skipped") >= 1
    out_i = idx.warmup_ingest(geometries=(32,))
    assert out_i == {}


# =====================================================================
# OOM replan: one replan through the copy twins, then typed failure
# =====================================================================
def test_oom_replan_uses_copy_twin(monkeypatch):
    """The replan pass dispatches through the NON-donating twins — a
    post-OOM retry can never consume the only copy of the arena."""
    calls = {"donated": 0, "copy": 0}
    orig_d, orig_c = S.search_fused_ragged, S.search_fused_ragged_copy

    def wd(*a, **kw):
        calls["donated"] += 1
        return orig_d(*a, **kw)

    def wc(*a, **kw):
        calls["copy"] += 1
        return orig_c(*a, **kw)

    monkeypatch.setattr(S, "search_fused_ragged", wd)
    monkeypatch.setattr(S, "search_fused_ragged_copy", wc)
    idx_c, emb = _build_multitenant()
    idx_f, _ = _build_multitenant(hbm_budget_bytes=1 << 34)
    reqs = _mt_reqs(emb)
    r_c = idx_c.search_fused_requests(list(reqs), **KW)
    INJECTOR.arm("plan.oom", times=1, exc=oom_error)
    r_f = idx_f.search_fused_requests(list(reqs), **KW)
    assert calls["copy"] >= 2          # the replan's split sub-dispatches
    _assert_results_equal(r_c, r_f)
    _assert_state_bitwise(idx_c, idx_f)
    assert idx_f.telemetry.counter_total("plan.oom_replans") == 1


def test_oom_replan_exhausted_raises_planinfeasible():
    """A second OOM on the replanned pass gives up typed — never an
    unbounded replan loop."""
    idx, emb = _build_multitenant(hbm_budget_bytes=1 << 34)
    INJECTOR.arm("plan.oom", times=10, exc=oom_error)
    with pytest.raises(PlanInfeasible):
        idx.search_fused_requests(_mt_reqs(emb), **KW)
    # bounded: one original pass + one replan pass, never 10 fires
    assert INJECTOR.fired("plan.oom") <= 3
    INJECTOR.clear()
    r = idx.search_fused_requests(_mt_reqs(emb, boost=False), **KW)
    assert all(x.ids for x in r)       # the index survived it all


# =====================================================================
# planned ingest split (mega-batch → sub-dispatches)
# =====================================================================
def test_ingest_plan_decision_and_calibration_feedback():
    idx, _ = _build_multitenant(hbm_budget_bytes=1 << 34)
    d = idx.plan_ingest(64)
    assert d.fused
    m = idx.planner.model
    g = idx._ingest_geometry(64)
    tight = int(m.predict(g.with_(batch=16)) / 0.9) + 4096
    idx2, _ = _build_multitenant(hbm_budget_bytes=tight)
    d2 = idx2.plan_ingest(64)
    assert d2.splits > 1               # the drain will sub-batch
    with pytest.raises(PlanInfeasible):
        idx2.planner.check_feasible(
            idx2._ingest_geometry(64).with_(rows=1 << 24),
            chunkable=False)


def test_memory_system_ingest_split_lands_all_facts(tmp_db, monkeypatch):
    """A planner-split consolidation mega-batch lands every fact exactly
    once (the in-dispatch dedup probe keeps sub-batches idempotent) and
    records the planned ingest dispatches."""
    from lazzaro_tpu.config import MemoryConfig
    from lazzaro_tpu.core.memory_system import MemorySystem
    from tests.test_fused_ingest import ClusteredEmb, QueueLLM

    ms = MemorySystem(
        enable_async=False, db_dir=tmp_db, verbose=False,
        load_from_disk=False, llm_provider=QueueLLM(4),
        embedding_provider=ClusteredEmb(), auto_prune=False,
        max_buffer_size=10_000,
        config=MemoryConfig(journal=True, auto_consolidate=False,
                            decay_rate=0.0,
                            hbm_budget_bytes=1 << 34))
    monkeypatch.setattr(
        type(ms.index), "plan_ingest",
        lambda self, n, link_k=3: PlanDecision(True, 2, 0, 0, 0,
                                               "test-forced"))
    ms.start_conversation()
    ms.add_to_short_term("turn one", "semantic", 0.6)
    ms.end_conversation()
    found = sum(1 for shard in ms.shards.values()
                for n in shard.nodes.values()
                if n.content.startswith("fact "))
    assert found == 4                  # all facts landed exactly once
    assert ms.telemetry.counter_total("plan.split_dispatches") >= 2
    ms.close()


def test_planner_stats_and_geometry_roundtrip():
    idx, _ = _build_multitenant(hbm_budget_bytes=1 << 30)
    idx.search_fused_requests(
        _mt_reqs(_vecs(128, 0), per_tenant=1, boost=False), **KW)
    st = idx.planner.stats()
    assert st["active"] and st["decisions"] >= 1
    g = idx._serve_geometry(8, "exact", 128)
    assert g.kind == "serve" and g.rows == 256 and g.mesh_parts == 1
