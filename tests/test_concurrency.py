"""Concurrency stress: the single-writer + mutex design under real threads.

The reference's ThreadPoolExecutor mutates shards/counters unlocked — a
data race SURVEY §5 says to design away. These tests hammer the orchestrator
from concurrent reader threads while background consolidations run, then
check structural invariants that unsynchronized mutation would violate.
"""

import threading

import numpy as np
import pytest

from lazzaro_tpu.core.memory_system import MemorySystem


def _invariants(ms):
    """Host graph ↔ arena coherence checks."""
    # Every host node has exactly one arena row, and vice versa (this user).
    host_ids = set(ms.buffer.nodes.keys())
    arena_ids = {q.partition(":")[2] for q in
                 ms.index.tenant_nodes.get(ms.user_id, set())}
    assert host_ids == arena_ids, (host_ids ^ arena_ids)
    # id maps are mutually inverse.
    for nid, row in ms.index.id_to_row.items():
        assert ms.index.row_to_id[row] == nid
    # No row is both free and allocated.
    free = set(ms.index._free_rows)
    used = set(ms.index.row_to_id)
    assert not (free & used)
    # Node counter never collides with an existing id.
    assert f"node_{ms.node_counter + 1}" not in host_ids


def test_concurrent_searches_during_async_ingest(tmp_path):
    ms = MemorySystem(enable_async=True, db_dir=str(tmp_path / "db"),
                      verbose=False, load_from_disk=False)
    errors = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            try:
                ms.search_memories("data engineer hiking cat", limit=3)
                ms.get_stats()
            except Exception as e:          # pragma: no cover
                errors.append(e)
                return

    threads = [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for i in range(6):
            ms.start_conversation()
            ms.chat(f"Fact number {i}: I enjoy topic {i} very much.")
            ms.end_conversation()           # async consolidation each time
    finally:
        ms._drain_background()
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert not errors, errors
    _invariants(ms)
    assert len(ms.buffer.nodes) > 0
    ms.close()


def test_interleaved_users_with_async_worker(tmp_path):
    """switch_user barriers: facts never leak across tenants even when
    consolidations queue up behind each other."""
    ms = MemorySystem(enable_async=True, db_dir=str(tmp_path / "db"),
                      verbose=False, load_from_disk=False)
    for user, fact in [("alice", "Alice plays violin in an orchestra."),
                       ("bob", "Bob repairs vintage motorcycles."),
                       ("alice", "Alice is learning Italian.")]:
        ms.switch_user(user)
        ms.start_conversation()
        ms.chat(fact)
        ms.end_conversation()
    ms._drain_background()

    ms.switch_user("alice")
    _invariants(ms)
    alice = " ".join(n.content for n in ms.buffer.nodes.values())
    assert "violin" in alice and "motorcycles" not in alice
    ms.switch_user("bob")
    _invariants(ms)
    bob = " ".join(n.content for n in ms.buffer.nodes.values())
    assert "motorcycles" in bob and "violin" not in bob
    ms.close()


def test_stats_expose_index_and_provider_health(tmp_path):
    from lazzaro_tpu.core.resilience import ResilientLLM

    class DeadLLM:
        def completion(self, messages, response_format=None):
            raise ConnectionError("down")

    ms = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db"),
                      verbose=False, load_from_disk=False,
                      llm_provider=ResilientLLM(DeadLLM(), max_retries=0))
    ms.start_conversation()
    ms.chat("I collect rare stamps.")
    ms.end_conversation()
    stats = ms.get_stats()
    assert stats["index"]["rows"] == len(ms.index)
    assert stats["index"]["dim"] == ms.embed_dim
    assert stats["providers"]["llm"] == "ResilientLLM"
    assert stats["providers"]["llm_health"]["fallback_calls"] > 0
    assert stats["providers"]["embedder_health"] is None   # plain embedder
    ms.close()


def test_concurrent_serving_modes_during_async_ingest(tmp_path):
    """int8 shadow refresh + IVF residual bookkeeping under concurrent
    readers while background consolidations mutate the arena: no crashes,
    no invariant violations, and retrieval keeps answering. (The serving
    shadows are allowed to be one write stale by design — the assertions
    here are about structural integrity, not freshness.)"""
    from lazzaro_tpu.config import MemoryConfig

    ms = MemorySystem(enable_async=True, db_dir=str(tmp_path / "db"),
                      verbose=False, load_from_disk=False,
                      config=MemoryConfig(journal=False, int8_serving=True,
                                          ivf_serving=4, pq_serving=True))
    # force the IVF/PQ hooks live even though the arena is tiny: build
    # won't trigger (below _IVF_MIN_ROWS) but the fresh/routed/pack
    # bookkeeping runs
    errors = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            try:
                ms.search_memories("engineer data project")
                ms.search_memories_batch(["alpha", "beta", "gamma"])
            except Exception as e:          # noqa: BLE001
                errors.append(e)
                return

    threads = [threading.Thread(target=reader, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for c in range(6):
            ms.start_conversation()
            ms.chat(f"I work on project {c} as a data engineer.")
            ms.end_conversation()
        # drain while readers are STILL live: the queued consolidations'
        # arena mutations are exactly the race window under test — with a
        # bounded wait, so a drain deadlock FAILS instead of hanging pytest
        assert ms.background_executor is not None
        ms.background_executor.submit(lambda: None).result(timeout=60)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not any(t.is_alive() for t in threads), "reader deadlocked"
    assert not errors, errors[:1]
    _invariants(ms)
    hits = [n.content for n in ms.search_memories("data engineer")]
    assert hits
    ms.close()
