"""Pod-scale fused serving (ISSUE 5): single-chip vs mesh parity.

The full chat-turn retrieval program — masked super top-1 gate, main ANN
top-k, CSR neighbor gather, neighbor+access boost scatters — must run as
ONE distributed shard_map dispatch (``state.make_fused_sharded``) and be
BIT-IDENTICAL to the single-chip fused kernels: the shard-local cores are
the same code, the all_gather merge preserves top-k order, and boosts land
as shard-local scatters. These tests pin that parity at the state level
(exact / quant / IVF twins, gate-hit and gate-miss, boost numerics,
multi-tenant isolation) on 2- and 4-way host-device meshes, plus the
``ShardedMemoryIndex`` wiring: one dispatch per coalesced mega-batch
(jit-counter via the ``_dispatch`` hook) and the batch max-k keying that
fixes the old silent truncation when a request's ``k`` exceeded the
construction-time default.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from lazzaro_tpu.core import state as S
from lazzaro_tpu.core.index import build_host_csr, split_csr
from lazzaro_tpu.parallel.index import ShardedMemoryIndex
from lazzaro_tpu.parallel.mesh import make_mesh, shard_stacked
from lazzaro_tpu.serve import QueryScheduler, RetrievalRequest

D = 16
CAP = 127          # cap+1 = 128 divides both mesh shapes
K, CT, MN = 8, 5, 8


def _mesh(n):
    return make_mesh(("data",), (n,), devices=jax.devices()[:n])


def _arena(n_rows=90, seed=0, tenants=2, super_every=9):
    rng = np.random.default_rng(seed)
    st = S.init_arena(CAP, D, jnp.float32)
    emb = rng.standard_normal((n_rows, D)).astype(np.float32)
    rows = np.arange(n_rows, dtype=np.int32)
    tcol = (np.arange(n_rows) % tenants).astype(np.int32)
    sup = (np.arange(n_rows) % super_every == 0)
    st = S.arena_add_copy(st, jnp.asarray(rows), jnp.asarray(emb),
                          jnp.full((n_rows,), 0.5, jnp.float32),
                          jnp.zeros((n_rows,), jnp.float32),
                          jnp.zeros((n_rows,), jnp.int32),
                          jnp.zeros((n_rows,), jnp.int32),
                          jnp.asarray(tcol), jnp.asarray(sup))
    id_to_row = {f"n{i}": i for i in range(n_rows)}
    keys = ([(f"n{i}", f"n{i + 1}") for i in range(n_rows - 1)]
            + [(f"n{i}", f"n{(i * 7) % n_rows}")
               for i in range(0, n_rows, 5)])
    indptr, nbr = build_host_csr(keys, id_to_row, CAP + 1)
    return st, emb, indptr, nbr


def _queries(seed=1, q=8, tenants=2):
    rng = np.random.default_rng(seed)
    qv = rng.standard_normal((q, D)).astype(np.float32)
    q_valid = np.ones((q,), bool)
    q_valid[-1] = False
    tq = (np.arange(q) % tenants).astype(np.int32)
    gate_on = np.ones((q,), bool)
    boost_on = np.ones((q,), bool)
    return qv, q_valid, tq, gate_on, boost_on


def _shard_state(st, mesh):
    row = NamedSharding(mesh, P("data"))
    mat = NamedSharding(mesh, P("data", None))
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, mat if a.ndim == 2 else row), st)


def _shard_csr(indptr, nbr, mesh):
    stk = shard_stacked(mesh, "data")
    ish, nsh = split_csr(indptr, nbr, mesh.shape["data"])
    return jax.device_put(ish, stk), jax.device_put(nsh, stk)


_TAIL = (jnp.float32(1000.0), jnp.float32(0.4), jnp.float32(0.05),
         jnp.float32(0.02))


@pytest.mark.parametrize("n_dev", [2, 4])
def test_exact_mode_bit_identical_to_single_chip(n_dev):
    """Packed readback AND post-serve boost columns (salience, access
    counts, freshness) must match the single-chip ``search_fused`` bit for
    bit — gate verdicts, neighbor dedup, and multi-tenant masks included."""
    mesh = _mesh(n_dev)
    st, emb, indptr, nbr = _arena()
    qv, q_valid, tq, gate_on, boost_on = _queries()
    args = (jnp.asarray(qv), jnp.asarray(q_valid), jnp.asarray(tq),
            jnp.asarray(gate_on), jnp.asarray(boost_on)) + _TAIL
    st1, p1 = S.search_fused_copy(st, jnp.asarray(indptr), jnp.asarray(nbr),
                                  *args, k=K, cap_take=CT, max_nbr=MN)
    kern = S.make_fused_sharded(mesh, "data", k=K, cap_take=CT, max_nbr=MN,
                                mode="exact")
    ish, nsh = _shard_csr(indptr, nbr, mesh)
    st2, p2 = kern.serve_copy(_shard_state(st, mesh), (), ish, nsh, *args)
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))
    for col in ("salience", "access_count", "last_accessed"):
        np.testing.assert_array_equal(np.asarray(getattr(st1, col)),
                                      np.asarray(getattr(st2, col)))


def test_read_twin_matches_and_mutates_nothing():
    mesh = _mesh(4)
    st, emb, indptr, nbr = _arena()
    qv, q_valid, tq, gate_on, _ = _queries()
    r1 = S.search_fused_read(st, jnp.asarray(indptr), jnp.asarray(nbr),
                             jnp.asarray(qv), jnp.asarray(q_valid),
                             jnp.asarray(tq), jnp.asarray(gate_on),
                             jnp.float32(0.4), k=K, cap_take=CT, max_nbr=MN)
    kern = S.make_fused_sharded(mesh, "data", k=K, cap_take=CT, max_nbr=MN,
                                mode="exact")
    ish, nsh = _shard_csr(indptr, nbr, mesh)
    st_sh = _shard_state(st, mesh)
    sal_before = np.asarray(st_sh.salience)
    r2 = kern.read(st_sh, (), ish, nsh, jnp.asarray(qv),
                   jnp.asarray(q_valid), jnp.asarray(tq),
                   jnp.asarray(gate_on), jnp.float32(0.4))
    np.testing.assert_array_equal(np.asarray(r1), np.asarray(r2))
    np.testing.assert_array_equal(sal_before, np.asarray(st_sh.salience))


def test_quant_mode_parity_exhaustive_slack():
    """With slack >= live rows the int8 coarse stage is exhaustive on both
    sides, so the sharded quant twin must match the single-chip quant
    kernel exactly (scores come from the same exact rescore)."""
    from lazzaro_tpu.ops.quant import quantize_rows

    mesh = _mesh(4)
    st, emb, indptr, nbr = _arena()
    qv, q_valid, tq, gate_on, boost_on = _queries()
    q8, scale = quantize_rows(st.emb)
    slack = CAP + 1
    args = (jnp.asarray(qv), jnp.asarray(q_valid), jnp.asarray(tq),
            jnp.asarray(gate_on), jnp.asarray(boost_on)) + _TAIL
    st1, p1 = S.search_fused_quant_copy(
        st, q8, scale, jnp.asarray(indptr), jnp.asarray(nbr), *args,
        k=K, slack=slack, cap_take=CT, max_nbr=MN)
    kern = S.make_fused_sharded(mesh, "data", k=K, cap_take=CT, max_nbr=MN,
                                mode="quant", slack=slack)
    ish, nsh = _shard_csr(indptr, nbr, mesh)
    row = NamedSharding(mesh, P("data"))
    mat = NamedSharding(mesh, P("data", None))
    st2, p2 = kern.serve_copy(
        _shard_state(st, mesh),
        (jax.device_put(q8, mat), jax.device_put(scale, row)),
        ish, nsh, *args)
    np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))
    for col in ("salience", "access_count", "last_accessed"):
        np.testing.assert_array_equal(np.asarray(getattr(st1, col)),
                                      np.asarray(getattr(st2, col)))


def test_ivf_mode_parity_full_probe():
    """nprobe == n_clusters makes the candidate set exhaustive on both
    sides; scores are exact in both kernels, so live results and boost
    columns must agree (row order at equal scores may differ across
    candidate layouts, so compare sets + numerics)."""
    from lazzaro_tpu.ops import ivf as IVF

    mesh = _mesh(4)
    st, emb, indptr, nbr = _arena()
    qv, q_valid, tq, gate_on, boost_on = _queries()
    ivf = IVF.build_ivf(st.emb, np.asarray(st.alive), n_clusters=8, iters=4)
    sup_rows = np.flatnonzero(np.asarray(st.is_super)).tolist()
    extras = IVF.pack_extras(np.asarray(ivf.residual), [], sup_rows)
    nprobe = ivf.n_clusters
    args = (jnp.asarray(qv), jnp.asarray(q_valid), jnp.asarray(tq),
            jnp.asarray(gate_on), jnp.asarray(boost_on)) + _TAIL
    st1, p1 = S.search_fused_ivf_copy(
        st, None, ivf.centroids, ivf.members, jnp.asarray(extras),
        jnp.asarray(indptr), jnp.asarray(nbr), *args,
        k=K, nprobe=nprobe, slack=8, cap_take=CT, max_nbr=MN)
    part = (CAP + 1) // 4
    mem_sh, ext_sh = IVF.shard_serve_tables(np.asarray(ivf.members), extras,
                                            4, part)
    kern = S.make_fused_sharded(mesh, "data", k=K, cap_take=CT, max_nbr=MN,
                                mode="ivf", slack=8, nprobe=nprobe)
    stk = shard_stacked(mesh, "data")
    ish, nsh = _shard_csr(indptr, nbr, mesh)
    st2, p2 = kern.serve_copy(
        _shard_state(st, mesh),
        (jax.device_put(ivf.centroids, NamedSharding(mesh, P())),
         jax.device_put(mem_sh, stk), jax.device_put(ext_sh, stk)),
        ish, nsh, *args)
    p1, p2 = np.asarray(p1), np.asarray(p2)
    np.testing.assert_allclose(p1[:, 0], p2[:, 0], atol=1e-6)   # gate score
    np.testing.assert_array_equal(p1[:, -1], p2[:, -1])         # fast bit
    np.testing.assert_allclose(p1[:, 2:2 + K], p2[:, 2:2 + K], atol=1e-6)
    for col in ("salience", "access_count", "last_accessed"):
        np.testing.assert_array_equal(np.asarray(getattr(st1, col)),
                                      np.asarray(getattr(st2, col)))


# ---------------------------------------------------------- index wiring
def _basis(i):
    v = np.zeros(D, np.float32)
    v[i % D] = 1.0
    return v


def _filled_index(mesh, **kw):
    idx = ShardedMemoryIndex(mesh, dim=D, capacity=CAP, dtype=np.float32,
                             **kw)
    rng = np.random.default_rng(3)
    emb_a = rng.standard_normal((12, D)).astype(np.float32)
    emb_b = rng.standard_normal((6, D)).astype(np.float32)
    idx.add([f"a{i}" for i in range(12)], emb_a, "alice")
    idx.add([f"b{i}" for i in range(6)], emb_b, "bob")
    idx.add_edges([(f"a{i}", f"a{i + 1}", 0.7) for i in range(11)])
    return idx, emb_a, emb_b


def test_serve_requests_one_distributed_dispatch_and_boosts():
    """The coalesced mixed-tenant batch costs exactly ONE distributed
    dispatch (the donated fused program — counted via the ``_dispatch``
    hook every device entry goes through), applies the access/neighbor
    boosts on device, and keeps tenants isolated."""
    mesh = _mesh(4)
    idx, emb_a, emb_b = _filled_index(mesh)
    reqs = [RetrievalRequest(query=emb_a[1], tenant="alice", k=3,
                             boost=True),
            RetrievalRequest(query=emb_b[0], tenant="bob", k=2, boost=True),
            RetrievalRequest(query=emb_a[4], tenant="alice", k=3)]
    idx.serve_requests(reqs)                   # warm/compile
    calls = {"n": 0}
    orig = idx._dispatch

    def counting(fn, *a, **kw):
        calls["n"] += 1
        return orig(fn, *a, **kw)

    idx._dispatch = counting
    acc_before = np.asarray(idx.state.access_count).copy()
    res = idx.serve_requests(reqs)
    assert calls["n"] == 1
    assert res[0].ids[0] == "a1" and all(i.startswith("a") for i in res[0].ids)
    assert res[1].ids[0] == "b0" and all(i.startswith("b") for i in res[1].ids)
    assert res[0].boosted and res[1].boosted and not res[2].boosted
    acc_after = np.asarray(idx.state.access_count)
    boosted_rows = [idx.id_to_row[i] for i in res[0].ids + res[1].ids]
    for r in boosted_rows:
        assert acc_after[r] >= acc_before[r] + 1
    # each boosted query bumps its top cap_take rows exactly once (the
    # classic per-turn semantics), and the no-boost request adds nothing
    assert (acc_after.sum() - acc_before.sum()
            == 2 * idx.cap_take)


def test_pure_read_batch_takes_read_twin_single_dispatch():
    mesh = _mesh(2)
    idx, emb_a, _ = _filled_index(mesh)
    reqs = [RetrievalRequest(query=emb_a[2], tenant="alice", k=4)]
    idx.serve_requests(reqs)
    sal_before = np.asarray(idx.state.salience).copy()
    calls = {"n": 0}
    orig = idx._dispatch

    def counting(fn, *a, **kw):
        calls["n"] += 1
        return orig(fn, *a, **kw)

    idx._dispatch = counting
    res = idx.serve_requests(reqs)
    assert calls["n"] == 1
    assert res[0].ids[0] == "a2"
    np.testing.assert_array_equal(sal_before, np.asarray(idx.state.salience))


def test_gate_verdict_reaches_pod_results():
    """A super row above the 0.4 gate flips ``fast`` on (and suppresses the
    device boosts for that query), below it stays off — the verdict the
    old pod path silently dropped."""
    mesh = _mesh(4)
    idx = ShardedMemoryIndex(mesh, dim=D, capacity=CAP, dtype=np.float32)
    idx.add(["s0"], _basis(0).reshape(1, -1), "u", supers=[True])
    idx.add(["m1", "m2"], np.stack([_basis(1), _basis(2)]), "u")
    hit = idx.serve_requests([RetrievalRequest(
        query=_basis(0), tenant="u", k=2, gate_enabled=True, boost=True)])[0]
    assert hit.fast and hit.gate_id == "s0" and hit.gate_score > 0.4
    assert not hit.boosted                     # host owns the fast path
    miss = idx.serve_requests([RetrievalRequest(
        query=_basis(3), tenant="u", k=2, gate_enabled=True, boost=True)])[0]
    assert not miss.fast
    # gate disabled: verdict must stay off even on a perfect super match
    off = idx.serve_requests([RetrievalRequest(
        query=_basis(0), tenant="u", k=2, gate_enabled=False)])[0]
    assert not off.fast


def test_request_k_above_default_is_not_truncated():
    """Satellite regression: the old pod path truncated every request to
    the construction-time ``k``; the kernel is now keyed on the batch
    max-k (pow2-bucketed). Covers BOTH the fused and the classic path."""
    for fused in (True, False):
        mesh = _mesh(4)
        idx = ShardedMemoryIndex(mesh, dim=D, capacity=CAP,
                                 dtype=np.float32, k=4, serve_fused=fused)
        rng = np.random.default_rng(5)
        n = 20
        idx.add([f"x{i}" for i in range(n)],
                rng.standard_normal((n, D)).astype(np.float32), "u")
        res = idx.serve_requests([RetrievalRequest(
            query=rng.standard_normal(D).astype(np.float32), tenant="u",
            k=12)])[0]
        assert len(res.ids) == 12, (fused, len(res.ids))
        # and mixed-k batches demux each request at its own k
        res2 = idx.serve_requests([
            RetrievalRequest(query=rng.standard_normal(D).astype(np.float32),
                             tenant="u", k=2),
            RetrievalRequest(query=rng.standard_normal(D).astype(np.float32),
                             tenant="u", k=11)])
        assert len(res2[0].ids) == 2 and len(res2[1].ids) == 11


def test_index_int8_and_ivf_modes_serve_sane_results():
    """int8 and IVF pod modes: same top-1 on well-separated data, one
    dispatch, and the IVF extras keep fresh rows visible."""
    mesh = _mesh(4)
    for mode_kw in (dict(int8_serving=True), dict()):
        idx = ShardedMemoryIndex(mesh, dim=D, capacity=CAP,
                                 dtype=np.float32, **mode_kw)
        ids = [f"v{i}" for i in range(24)]
        embs = np.stack([_basis(i) + 0.05 * np.arange(D) for i in range(24)])
        idx.add(ids, embs, "u")
        if not mode_kw:
            assert idx.ivf_build(n_clusters=4, nprobe=4)
        res = idx.serve_requests([RetrievalRequest(
            query=embs[7], tenant="u", k=3)])[0]
        assert res.ids[0] == "v7"
        if not mode_kw:
            # fresh row added AFTER the build serves exactly via extras
            idx.add(["fresh"], (_basis(3) * 2).reshape(1, -1), "u")
            res = idx.serve_requests([RetrievalRequest(
                query=_basis(3) * 2, tenant="u", k=2)])[0]
            assert res.ids[0] == "fresh"


def test_scheduler_mega_batch_reaches_pod_path_once():
    """QueryScheduler coalescing composes with the fused pod path: many
    concurrent requests across tenants flush as batches, each batch ONE
    distributed dispatch."""
    mesh = _mesh(4)
    idx, emb_a, emb_b = _filled_index(mesh)
    idx.serve_requests([RetrievalRequest(query=emb_a[0], tenant="alice",
                                         k=3)])       # warm the kernel
    calls = {"n": 0}
    orig = idx._dispatch

    def counting(fn, *a, **kw):
        calls["n"] += 1
        return orig(fn, *a, **kw)

    idx._dispatch = counting
    sched = QueryScheduler(idx.serve_requests, max_batch=16, max_wait_us=500)
    try:
        futures = sched.submit_many(
            [RetrievalRequest(query=emb_a[i % 12], tenant="alice", k=3)
             for i in range(8)]
            + [RetrievalRequest(query=emb_b[i % 6], tenant="bob", k=2)
               for i in range(8)])
        res = [f.result(timeout=30) for f in futures]
    finally:
        sched.close()
    assert all(r.ids for r in res)
    assert all(i.startswith("a") for r in res[:8] for i in r.ids)
    assert all(i.startswith("b") for r in res[8:] for i in r.ids)
    batches = sched.stats()["batches_flushed"]
    assert calls["n"] == batches               # one dispatch per mega-batch
