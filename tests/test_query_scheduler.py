"""Cross-request query batching (serve.QueryScheduler) + the shared
time/size flush policy (utils.batching.FlushPolicy) on both the serving and
the ingest side."""

import tempfile
import threading
import time

import numpy as np
import pytest

from lazzaro_tpu.config import MemoryConfig
from lazzaro_tpu.core.memory_system import MemorySystem
from lazzaro_tpu.serve import (QueryScheduler, RetrievalRequest,
                               RetrievalResult)
from lazzaro_tpu.utils.batching import FlushPolicy, IngestCoalescer
from tests.test_fused_ingest import ClusteredEmb, QueueLLM


# ------------------------------------------------------------- FlushPolicy
def test_flush_policy_size_and_time():
    p = FlushPolicy(max_items=4, max_wait_s=10.0)
    t0 = 1000.0
    p.note_add(t0)
    assert not p.should_flush(1, t0 + 1)          # small AND young: wait
    assert p.should_flush(4, t0 + 1)              # size threshold
    assert p.should_flush(1, t0 + 10.0)           # age threshold
    assert p.wait_remaining(t0 + 4) == pytest.approx(6.0)
    p.reset()
    assert p.wait_remaining(t0) == 3600.0         # empty: park the worker
    # explicit oldest overrides the internal tracker (scheduler pops
    # partial batches, so head-of-queue age is the caller's truth)
    assert p.should_flush(1, t0 + 3, oldest=t0 - 8)


def test_flush_policy_eager_mode():
    p = FlushPolicy(max_items=100, max_wait_s=0.0)
    p.note_add(0.0)
    assert p.should_flush(1, 0.0)                 # wait<=0: always flush
    assert not p.should_flush(0, 0.0)             # ...except when empty


def test_coalescer_time_policy():
    c = IngestCoalescer(max_facts=100, max_wait_s=30.0)
    t0 = 2000.0
    c.add_conversation([{"content": "a"}], now=t0)
    assert not c.should_flush(now=t0 + 1)          # trickle: hold
    assert c.should_flush(now=t0 + 31)             # aged out: ship
    for i in range(100):
        c.add_conversation([{"content": f"b{i}"}], now=t0 + 2)
    assert c.should_flush(now=t0 + 2)              # full: ship now
    c.drain()
    c.add_conversation([{"content": "c"}], now=t0 + 60)
    # drain reset the clock: the new lone fact is young again
    assert not c.should_flush(now=t0 + 61)


# ---------------------------------------------------------- QueryScheduler
def _echo_executor(reqs):
    out = []
    for r in reqs:
        res = RetrievalResult()
        res.ids = [f"{r.tenant}:{int(r.query[0])}"]
        res.scores = [1.0]
        out.append(res)
    return out


def test_scheduler_demuxes_in_order():
    s = QueryScheduler(_echo_executor, max_batch=8, max_wait_us=1000)
    try:
        reqs = [RetrievalRequest(query=np.asarray([i], np.float32),
                                 tenant="u") for i in range(20)]
        futures = s.submit_many(reqs)
        got = [f.result(timeout=10).ids[0] for f in futures]
        assert got == [f"u:{i}" for i in range(20)]
        stats = s.stats()
        assert stats["requests_served"] == 20
        # max_batch=8 bounds every flush
        assert stats["max_batch_seen"] <= 8
    finally:
        s.close()


def test_scheduler_coalesces_while_executor_busy():
    """Requests arriving while a flush is in flight pile up and ship as one
    dense batch — the core amortization claim."""
    release = threading.Event()
    batches = []

    def slow_executor(reqs):
        batches.append(len(reqs))
        if len(batches) == 1:
            release.wait(timeout=10)
        return _echo_executor(reqs)

    s = QueryScheduler(slow_executor, max_batch=64, max_wait_us=500)
    try:
        first = s.submit(RetrievalRequest(query=np.zeros(1, np.float32),
                                          tenant="u"))
        time.sleep(0.05)                       # worker is now blocked
        rest = s.submit_many([
            RetrievalRequest(query=np.asarray([i], np.float32), tenant="u")
            for i in range(10)])
        release.set()
        first.result(timeout=10)
        for f in rest:
            f.result(timeout=10)
        assert batches[0] == 1
        assert batches[1] == 10                # coalesced into ONE batch
    finally:
        s.close()


def test_scheduler_propagates_executor_errors():
    def boom(reqs):
        raise RuntimeError("kernel exploded")

    s = QueryScheduler(boom, max_batch=4, max_wait_us=100)
    try:
        f = s.submit(RetrievalRequest(query=np.zeros(1, np.float32),
                                      tenant="u"))
        with pytest.raises(RuntimeError, match="kernel exploded"):
            f.result(timeout=10)
    finally:
        s.close()


def test_scheduler_close_drains_then_rejects():
    s = QueryScheduler(_echo_executor, max_batch=4, max_wait_us=50_000)
    futures = s.submit_many([
        RetrievalRequest(query=np.asarray([i], np.float32), tenant="u")
        for i in range(3)])
    s.close()                                  # drains pending before exit
    assert [f.result(timeout=1).ids[0] for f in futures] == \
        ["u:0", "u:1", "u:2"]
    assert s.closed
    with pytest.raises(RuntimeError):
        s.submit(RetrievalRequest(query=np.zeros(1, np.float32), tenant="u"))


def test_scheduler_flush_barrier():
    s = QueryScheduler(_echo_executor, max_batch=64, max_wait_us=200_000)
    try:
        futures = s.submit_many([
            RetrievalRequest(query=np.asarray([i], np.float32), tenant="u")
            for i in range(5)])
        s.flush(timeout=10)                    # beats the 200 ms wait
        assert all(f.done() for f in futures)
    finally:
        s.close()


# ----------------------------------------- ingest deferral (MemorySystem)
def _system(tmp, wait_s):
    ms = MemorySystem(
        enable_async=False, db_dir=tmp, verbose=False, load_from_disk=False,
        llm_provider=QueueLLM(6), embedding_provider=ClusteredEmb(),
        auto_prune=False, max_buffer_size=10_000,
        config=MemoryConfig(journal=False, auto_consolidate=False,
                            decay_rate=0.0, ingest_flush_wait_s=wait_s))
    return ms


def test_trickle_ingest_defers_then_coalesces():
    """With ingest_flush_wait_s > 0 a lone conversation's facts wait in the
    coalescer (journal-visible) instead of draining immediately; the next
    consolidation inside the window lands BOTH conversations in one fused
    mega-batch; close() force-drains whatever remains."""
    with tempfile.TemporaryDirectory() as tmp:
        ms = _system(tmp, wait_s=3600.0)
        ms.start_conversation()
        ms.add_to_short_term("conv 0", "episodic", 0.7)
        ms.end_conversation()
        assert ms.buffer.size()[0] == 0            # deferred, not ingested
        assert len(ms._ingest_coalescer) == 6
        assert ms._deferred_batches                # still journal-visible
        # aging past the window flushes on the next consolidation
        ms._ingest_coalescer.policy._oldest -= 7200.0
        ms.start_conversation()
        ms.add_to_short_term("conv 1", "episodic", 0.7)
        ms.end_conversation()
        assert ms.buffer.size()[0] == 12           # both conversations
        assert len(ms._ingest_coalescer) == 0
        assert not ms._deferred_batches
        ms.close()


def test_close_force_drains_deferred_facts():
    with tempfile.TemporaryDirectory() as tmp:
        ms = _system(tmp, wait_s=3600.0)
        ms.start_conversation()
        ms.add_to_short_term("conv 0", "episodic", 0.7)
        ms.end_conversation()
        assert ms.buffer.size()[0] == 0
        ms.close()                                 # force-drain
        assert ms.buffer.size()[0] == 6


def test_eager_default_preserves_behavior():
    with tempfile.TemporaryDirectory() as tmp:
        ms = _system(tmp, wait_s=0.0)
        ms.start_conversation()
        ms.add_to_short_term("conv 0", "episodic", 0.7)
        ms.end_conversation()
        assert ms.buffer.size()[0] == 6            # ingested immediately
        ms.close()


# ------------------------------------------------- sharded serve executor
def test_sharded_index_serve_requests():
    import jax
    from jax.sharding import Mesh
    from lazzaro_tpu.parallel.index import ShardedMemoryIndex

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    idx = ShardedMemoryIndex(mesh, dim=8, capacity=64, k=4)
    rng = np.random.default_rng(0)
    emb_a = rng.standard_normal((4, 8)).astype(np.float32)
    emb_b = rng.standard_normal((2, 8)).astype(np.float32)
    idx.add([f"a{i}" for i in range(4)], emb_a, "ta")
    idx.add([f"b{i}" for i in range(2)], emb_b, "tb")

    sched = QueryScheduler(idx.serve_requests, max_batch=8, max_wait_us=500)
    try:
        futures = sched.submit_many([
            RetrievalRequest(query=emb_a[1], tenant="ta", k=2),
            RetrievalRequest(query=emb_b[0], tenant="tb", k=1),
            RetrievalRequest(query=emb_a[3], tenant="ta", k=2),
        ])
        res = [f.result(timeout=30) for f in futures]
        assert res[0].ids[0] == "a1" and len(res[0].ids) == 2
        assert res[1].ids == ["b0"]                # tenant isolated
        assert res[2].ids[0] == "a3"
        assert all(i.startswith("a") for i in res[0].ids + res[2].ids)
    finally:
        sched.close()
