"""The orchestrator holds a 100k-node graph end-to-end: ingest through
``end_conversation`` batches (LLM extract → batch embed → batched dedup probe
→ batched arena insert → link matmuls → delta-segment store writes),
sub-10ms p50 ``search_memories``, and a columnar persistence round-trip with
closed-form decay replay.

This is the system-level scale claim (VERDICT round 1: "1M-node graph is
currently a kernel claim, not a system claim") exercised at 100k so it runs
in CI; the bench drives the same path at 1M on the real chip."""

# Compile-heavy (multi-second XLA compiles / 100k-row arenas): the
# default lane must stay inside a driver window; run the full lane
# with no -m filter for round gates.
pytestmark = __import__("pytest").mark.slow

import json
import time

import numpy as np
import pytest

from lazzaro_tpu import MemorySystem
from lazzaro_tpu.config import MemoryConfig

DIM = 32
FACTS_PER_CONV = 2_000
CONVS = 50
TOTAL = FACTS_PER_CONV * CONVS


class BulkEmbedder:
    """Deterministic near-orthogonal unit vectors keyed by the fact index
    embedded in the text ("fact <i>: ..."). Vectorized batch path."""

    dim = DIM

    def _vec(self, text: str) -> np.ndarray:
        idx = int(text.split(":")[0].split()[-1]) if text.startswith("fact") else hash(text) % (1 << 31)
        rng = np.random.default_rng(idx)
        v = rng.standard_normal(DIM).astype(np.float32)
        return v / np.linalg.norm(v)

    def embed(self, text):
        return self._vec(text).tolist()

    def batch_embed(self, texts):
        return [self._vec(t).tolist() for t in texts]


class QueueLLM:
    """Pops one canned extraction payload per completion call."""

    def __init__(self, payloads):
        self.payloads = list(payloads)

    def completion(self, messages, response_format=None):
        return self.payloads.pop(0) if self.payloads else json.dumps({"memories": []})

    def completion_stream(self, messages, response_format=None):
        yield self.completion(messages, response_format)


def _payload(conv: int) -> str:
    base = conv * FACTS_PER_CONV
    return json.dumps({"memories": [
        {"content": f"fact {base + i}: user detail number {base + i}",
         "type": "semantic", "salience": 0.6, "topic": "work"}
        for i in range(FACTS_PER_CONV)]})


@pytest.fixture(scope="module")
def big_system(tmp_path_factory):
    db = str(tmp_path_factory.mktemp("scale") / "db")
    ms = MemorySystem(
        enable_async=False,
        enable_hierarchy=False,
        auto_consolidate=False,
        load_from_disk=False,
        max_buffer_size=TOTAL * 2,
        db_dir=db,
        llm_provider=QueueLLM([_payload(c) for c in range(CONVS)]),
        embedding_provider=BulkEmbedder(),
        config=MemoryConfig(dtype="bfloat16", journal=False),
        verbose=False,
    )
    for c in range(CONVS):
        ms.start_conversation()
        ms.add_to_short_term(f"conversation {c} transcript", "episodic", 0.7)
        ms.end_conversation()
    yield ms, db
    ms.close()


def test_ingests_100k_nodes(big_system):
    ms, _ = big_system
    nodes, edges = ms.buffer.size()
    # random unit vectors at dim=32 can produce a handful of >0.95 dedups
    assert nodes > TOTAL * 0.99
    assert len(ms.index) == nodes
    assert edges > 0          # linking ran at scale


def test_search_p50_under_10ms(big_system):
    ms, _ = big_system
    # warm the compiled search path
    ms.search_memories("fact 123: user detail number 123")
    lat = []
    for i in range(30):
        t0 = time.perf_counter()
        hits = ms.search_memories(f"fact {i * 997}: user detail number {i * 997}")
        lat.append((time.perf_counter() - t0) * 1e3)
        assert hits and hits[0].content.startswith(f"fact {i * 997}:")
    p50 = float(np.percentile(lat, 50))
    assert p50 < 10.0, f"search_memories p50 {p50:.2f}ms at {TOTAL} nodes"


def test_saves_are_incremental_deltas(big_system):
    ms, db = big_system
    from lazzaro_tpu.core.store import ArrowStore
    store: ArrowStore = ms.store
    man = store._load_manifest("nodes", "default")
    # The graph was built by 50 conversations; a delete-all+rewrite design
    # would have written ~2.5M cumulative rows. Delta segments + amortized
    # compaction keep the manifest shallow and the tail segments small.
    assert man is not None
    assert len(man["segments"]) < 40


def test_persistence_roundtrip_with_decay_replay(big_system):
    ms, db = big_system
    assert ms._decay_pass == CONVS
    ms2 = MemorySystem(
        enable_async=False, enable_hierarchy=False, auto_consolidate=False,
        load_from_disk=True, max_buffer_size=TOTAL * 2, db_dir=db,
        embedding_provider=BulkEmbedder(),
        config=MemoryConfig(dtype="bfloat16", journal=False), verbose=False)
    try:
        nodes, _ = ms2.buffer.size()
        n1, _ = ms.buffer.size()
        assert nodes == n1
        assert ms2._decay_pass == CONVS
        # host nodes come back slim: no per-node embedding lists
        some = ms2.buffer.get_node("node_1")
        assert some is not None and some.embedding is None
        # decay replay: a conversation-1 node missed ~49 sweeps; its stored
        # salience (stamped at write) must be replayed down on load
        expected = 0.2 + (0.6 - 0.2) * (1 - 0.01) ** (CONVS - 1)
        assert some.salience == pytest.approx(expected, abs=2e-2)
        hits = ms2.search_memories("fact 77777: user detail number 77777")
        assert hits and hits[0].content.startswith("fact 77777:")
    finally:
        ms2.close()


def test_serving_modes_at_100k(big_system):
    """int8 shadow and IVF coarse stage on the SAME 100k graph: exact hits
    on the well-separated fact vectors, and the IVF build actually runs at
    this scale (the arena is far past _IVF_MIN_ROWS)."""
    ms, _ = big_system
    probes = [i * 991 for i in range(20)]

    ms.index.int8_serving = True
    try:
        for p in probes:
            hits = ms.search_memories(f"fact {p}: user detail number {p}")
            assert hits and hits[0].content.startswith(f"fact {p}:"), p
    finally:
        ms.index.int8_serving = False
        ms.index._int8_shadow = None

    ms.index.ivf_nprobe = 8
    try:
        assert ms.index.ivf_maintenance()     # k-means over 100k rows
        got = 0
        for p in probes:
            hits = ms.search_memories(f"fact {p}: user detail number {p}")
            if hits and hits[0].content.startswith(f"fact {p}:"):
                got += 1
        # near-orthogonal random vectors are a worst case for IVF routing
        # (no cluster structure): self-lookup still lands >= 70% at
        # nprobe=8/C=256, and every miss is a routing miss, not corruption
        assert got >= 14, f"ivf self-recall {got}/20"
    finally:
        ms.index.ivf_nprobe = 0
        ms.index._ivf = None
