"""Protocol-conforming fake providers (reference test pattern, SURVEY §4(a)):
deterministic embeddings so similarity thresholds are exactly testable, and
canned-JSON LLMs so consolidation runs without any model."""

import json
from typing import Dict, List, Optional


class MockEmbedder:
    """Deterministic: known texts map to fixed orthogonal-ish vectors; two
    texts are near-duplicates iff mapped to the same basis vector."""

    def __init__(self, dim: int = 8, table: Optional[Dict[str, int]] = None):
        self.dim = dim
        self.table = table or {}

    def _vec(self, text: str) -> List[float]:
        idx = self.table.get(text, abs(hash(text)) % self.dim)
        v = [0.0] * self.dim
        v[idx % self.dim] = 1.0
        return v

    def embed(self, text: str) -> List[float]:
        return self._vec(text)

    def batch_embed(self, texts: List[str]) -> List[List[float]]:
        return [self._vec(t) for t in texts]


class MockLLM:
    """Returns canned responses; optionally keyed by a substring sniffer
    (reference test_profile_update.py pattern, SURVEY §4)."""

    def __init__(self, response: str = "ok", sniffers: Optional[Dict[str, str]] = None):
        self.response = response
        self.sniffers = sniffers or {}
        self.calls: List[List[Dict]] = []

    def completion(self, messages, response_format=None) -> str:
        self.calls.append(messages)
        joined = " ".join(m["content"] for m in messages)
        for needle, resp in self.sniffers.items():
            if needle in joined:
                return resp
        return self.response

    def completion_stream(self, messages, response_format=None):
        yield self.completion(messages, response_format)


def extraction_response(facts) -> str:
    """Build a canned fact-extraction JSON payload."""
    return json.dumps({"memories": facts})
