"""Pod-scale fused INGEST (ISSUE 9): single-chip vs mesh parity.

The full write-path program — dedup probe, intra-batch gram resolve, node
scatter, merge touch, both link scans, gated edge insert with prefix-sum
pool compaction, incremental int8 shadow update — must run as ONE
distributed shard_map dispatch (``state.make_ingest_fused_sharded``) and
be BIT-IDENTICAL to the single-chip ``ingest_dedup_fused``: the shard-
local scan cores are the same code, the grouped all_gather merge preserves
top-k order, and every write lands owner-chip-local. These tests pin that
parity at the state level (arena columns, edge pool, shadow, dedup
resolutions, overflow) on 2- and 4-way host-device meshes, plus the index
wiring: ``ShardedMemoryIndex.ingest`` fused-vs-classic semantic parity,
one distributed dispatch per coalesced mega-batch (jit counter), zero
added dispatches with telemetry on (the PR 6 guarantee extended to the
write path), ``MemoryIndex(mesh=...)`` routing, and ``warmup_ingest``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from lazzaro_tpu.core import state as S
from lazzaro_tpu.core.index import MemoryIndex
from lazzaro_tpu.parallel.index import ShardedMemoryIndex
from lazzaro_tpu.parallel.mesh import make_mesh

D = 16
CAP = 127          # cap+1 = 128 divides both mesh shapes
ECAP = 255
K = 3


def _mesh(n):
    return make_mesh(("data",), (n,), devices=jax.devices()[:n])


def _shard(pytree, mesh):
    row = NamedSharding(mesh, P("data"))
    mat = NamedSharding(mesh, P("data", None))
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, mat if a.ndim == 2 else row), pytree)


def _prefilled(n0=60, seed=0):
    """Arena with ``n0`` rows across 3 shard groups, some supers, plus an
    empty edge arena and a fresh int8 shadow."""
    from lazzaro_tpu.ops.quant import quantize_rows

    rng = np.random.default_rng(seed)
    arena = S.init_arena(CAP, D, jnp.float32)
    emb0 = rng.standard_normal((n0, D)).astype(np.float32)
    arena = S.arena_add_copy(
        arena, jnp.arange(n0, dtype=jnp.int32), jnp.asarray(emb0),
        jnp.full((n0,), 0.5, jnp.float32), jnp.zeros((n0,), jnp.float32),
        jnp.zeros((n0,), jnp.int32),
        jnp.asarray((np.arange(n0) % 3).astype(np.int32)),
        jnp.zeros((n0,), jnp.int32),
        jnp.asarray(np.arange(n0) % 9 == 0))
    edges = S.init_edges(ECAP)
    q8, scale = quantize_rows(arena.emb)
    return arena, edges, (q8, scale)


def _batch_args(arena, n=10, seed=3, pool_len=None):
    """A fact batch with one dup-of-existing, one intra-batch dup, one
    dup-of-the-dup, a sub-gate near-neighbor, and sentinel padding."""
    rng = np.random.default_rng(seed)
    emb = rng.standard_normal((n, D)).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    base5 = np.array(arena.emb[5], np.float32)
    base5 /= max(float(np.linalg.norm(base5)), 1e-9)
    emb[3] = base5 + 0.03 * rng.standard_normal(D)     # dup of row 5
    emb[7] = emb[2] + 0.03 * rng.standard_normal(D)    # dup of fact 2
    emb[8] = emb[7] + 0.03 * rng.standard_normal(D)    # dup-of-the-dup
    b10 = np.array(arena.emb[10], np.float32)
    b10 /= max(float(np.linalg.norm(b10)), 1e-9)
    emb[0] = 0.8 * b10 + 0.45 * rng.standard_normal(D)  # links, no dup

    rows = np.arange(60, 60 + n, dtype=np.int32)
    padded = S.pad_rows(rows, CAP)
    b = len(padded)
    emb_p = np.zeros((b, D), np.float32)
    emb_p[:n] = emb
    emb_p[n:, 0] = 1.0

    def pad(vals, fill=0.0, dt=np.float32):
        out = np.full((b,), fill, dt)
        out[:n] = vals
        return out

    chain_slots = np.full((b,), ECAP, np.int32)
    chain_slots[:n] = np.arange(10, 10 + n)
    worst = 2 * n * K
    pool_list = list(range(40, 40 + worst))
    if pool_len is None:
        pool_len = worst
    link_pool = np.full((worst + 1,), ECAP, np.int32)
    link_pool[:len(pool_list)] = pool_list
    return (jnp.asarray(padded), jnp.asarray(emb_p),
            jnp.asarray(pad([0.6] * n)), jnp.asarray(pad([1.0] * n)),
            jnp.asarray(pad([0] * n, 0, np.int32)),
            jnp.asarray(pad(np.arange(n) % 3, -1, np.int32)),
            jnp.asarray(pad([0] * n, -1, np.int32)),
            jnp.asarray(pad([False] * n, False, bool)),
            jnp.asarray(pad([0] * n, -1, np.int32)),
            jnp.asarray(chain_slots), jnp.asarray(link_pool),
            jnp.int32(pool_len), jnp.float32(2.0), jnp.int32(0),
            jnp.float32(0.95), jnp.float32(0.5), jnp.float32(0.4),
            jnp.float32(0.8), jnp.float32(1.0))


ARENA_COLS = ("emb", "salience", "timestamp", "last_accessed",
              "access_count", "type_id", "shard_id", "tenant_id", "alive",
              "is_super")
EDGE_COLS = ("src", "tgt", "weight", "co", "last_updated", "alive",
             "tenant_id")


def _assert_state_parity(a1, e1, a2, e2):
    """Arena + edge columns bit-identical EXCLUDING the sentinel row/slot
    (duplicate-index scatter order at the sentinel is compiler-defined)."""
    for col in ARENA_COLS:
        np.testing.assert_array_equal(
            np.asarray(getattr(a1, col))[:CAP],
            np.asarray(getattr(a2, col))[:CAP], err_msg=col)
    for col in EDGE_COLS:
        np.testing.assert_array_equal(
            np.asarray(getattr(e1, col))[:ECAP],
            np.asarray(getattr(e2, col))[:ECAP], err_msg="edge:" + col)


def _assert_readback_parity(out1, out2, n=10, n_modes=2):
    """Dedup verdicts, merge targets, chain sources, live rows' candidate
    triples, and the counter tail must match bit for bit (dup/pad rows'
    candidate scores are readback noise both sides discard)."""
    dup = np.asarray(out1[0])[:, 0]
    for wi in range(3):
        np.testing.assert_array_equal(np.asarray(out1[wi]),
                                      np.asarray(out2[wi]))
    live = ~dup.astype(bool)[:n]
    for mi in range(n_modes):
        s1 = np.asarray(out1[3 + 3 * mi])[:n][live]
        s2 = np.asarray(out2[3 + 3 * mi])[:n][live]
        lv = s1 > S.NEG_INF / 2
        np.testing.assert_array_equal(s1[lv], s2[lv])
        c1 = np.asarray(out1[3 + 3 * mi + 1])[:n][live]
        c2 = np.asarray(out2[3 + 3 * mi + 1])[:n][live]
        np.testing.assert_array_equal(c1[lv], c2[lv])
        np.testing.assert_array_equal(
            np.asarray(out1[3 + 3 * mi + 2])[:n][live],
            np.asarray(out2[3 + 3 * mi + 2])[:n][live])
    for ci in range(3 + 3 * n_modes, 6 + 3 * n_modes):
        np.testing.assert_array_equal(np.asarray(out1[ci])[0, 0],
                                      np.asarray(out2[ci])[0, 0])


@pytest.mark.parametrize("n_dev", [2, 4])
def test_sharded_ingest_bit_identical_to_single_chip(n_dev):
    """Arena columns, edge pool, int8 shadow, dedup resolutions, and the
    packed readback of the distributed ingest program must match the
    single-chip ``ingest_dedup_fused`` bit for bit."""
    arena, edges, shadow = _prefilled()
    args = _batch_args(arena)
    a1, e1, sh1, _, _, _, out1 = S.ingest_dedup_fused_copy(
        arena, edges, shadow, None, None, None, *args, k=K,
        shard_modes=(1, 0))
    dup = np.asarray(out1[0])[:10, 0]
    assert dup.sum() == 3, dup                 # the scenario does real work
    assert int(np.asarray(out1[10])[0, 0]) > 0  # some links accepted

    mesh = _mesh(n_dev)
    kern = S.make_ingest_fused_sharded(mesh, "data", k=K,
                                       shard_modes=(1, 0), with_shadow=True)
    row = NamedSharding(mesh, P("data"))
    mat = NamedSharding(mesh, P("data", None))
    a2, e2, q8b, sb, out2 = kern.ingest_copy(
        _shard(arena, mesh), _shard(edges, mesh),
        jax.device_put(shadow[0], mat), jax.device_put(shadow[1], row),
        *args)
    _assert_readback_parity(out1, out2)
    _assert_state_parity(a1, e1, a2, e2)
    np.testing.assert_array_equal(np.asarray(sh1[0])[:CAP],
                                  np.asarray(q8b)[:CAP])
    np.testing.assert_array_equal(np.asarray(sh1[1])[:CAP],
                                  np.asarray(sb)[:CAP])


def test_sharded_ingest_overflow_parity():
    """A pool smaller than the accepted-link count must raise the SAME
    in-kernel overflow flag, the same true prefix positions (so the host
    re-inserts exactly the overflowed edges), and the same edge-pool
    state on both paths."""
    arena, edges, shadow = _prefilled()
    args = _batch_args(arena, pool_len=2)      # force overflow
    a1, e1, _, _, _, _, out1 = S.ingest_dedup_fused_copy(
        arena, edges, None, None, None, None, *args, k=K,
        shard_modes=(1, 0))
    assert int(np.asarray(out1[9])[0, 0]) == 1  # overflow flag set
    mesh = _mesh(4)
    kern = S.make_ingest_fused_sharded(mesh, "data", k=K,
                                       shard_modes=(1, 0),
                                       with_shadow=False)
    a2, e2, out2 = kern.ingest_copy(_shard(arena, mesh),
                                    _shard(edges, mesh), *args)
    _assert_readback_parity(out1, out2)
    _assert_state_parity(a1, e1, a2, e2)


def test_donated_twin_matches_copy_twin():
    """The donated distributed program computes the same result as the
    copy twin (ownership handoff only, no numeric difference)."""
    mesh = _mesh(2)
    arena, edges, _ = _prefilled()
    args = _batch_args(arena)
    kern = S.make_ingest_fused_sharded(mesh, "data", k=K,
                                       shard_modes=(1, 0),
                                       with_shadow=False)
    a1, e1, out1 = kern.ingest_copy(_shard(arena, mesh),
                                    _shard(edges, mesh), *args)
    a2, e2, out2 = kern.ingest(_shard(arena, mesh), _shard(edges, mesh),
                               *args)
    _assert_readback_parity(out1, out2)
    _assert_state_parity(a1, e1, a2, e2)


# ------------------------------------------------------------ index wiring
_DIRS = np.random.default_rng(7).standard_normal((8, D)).astype(np.float32)
_DIRS /= np.linalg.norm(_DIRS, axis=1, keepdims=True)


def _clustered(n, seed):
    """Group-clustered vectors: intra-group cosine ~0.86 (> the 0.5 link
    gate, < the 0.95 dedup gate) so gated links do real work."""
    r = np.random.default_rng(seed)
    g = np.arange(n) % len(_DIRS)
    v = _DIRS[g] * 0.9 + 0.32 * r.standard_normal((n, D)).astype(np.float32)
    return (v / np.linalg.norm(v, axis=1, keepdims=True)).astype(np.float32)


def _pod_index(mesh, fused=True, **kw):
    idx = ShardedMemoryIndex(mesh, dim=D, capacity=CAP, dtype=np.float32,
                             edge_capacity=511, ingest_fused=fused, **kw)
    idx.add([f"p{i}" for i in range(24)], _clustered(24, 1), "u")
    return idx


def _ingest_batch(idx, prefix="f"):
    batch = _clustered(10, 2)
    batch[3] = (_clustered(24, 1)[3]
                + 0.03 * np.random.default_rng(9).standard_normal(D))
    batch[7] = (batch[2]
                + 0.03 * np.random.default_rng(10).standard_normal(D))
    return idx.ingest([f"{prefix}{i}" for i in range(10)], batch, "u",
                      dedup_gate=0.95, chain=True, link_k=3, link_gate=0.5)


@pytest.mark.parametrize("n_dev", [2, 4])
def test_pod_ingest_fused_matches_classic(n_dev):
    """``ShardedMemoryIndex.ingest`` fused vs the host-driven classic
    sequence: same created ids, same merge targets, same link edge set
    with matching weights, same chains — and the fused path costs ONE
    distributed dispatch where the classic pays several."""
    i1 = _pod_index(_mesh(n_dev), fused=True)
    i2 = _pod_index(_mesh(n_dev), fused=False)
    o1 = _ingest_batch(i1)
    o2 = _ingest_batch(i2)
    assert sorted(o1["created"]) == sorted(o2["created"])
    assert o1["merged"] == o2["merged"] and o1["merged"]
    assert sorted(o1["chains"]) == sorted(o2["chains"])
    l1 = sorted((s, t, w) for s, t, w in o1["links"])
    l2 = sorted((s, t, w) for s, t, w in o2["links"])
    assert [x[:2] for x in l1] == [x[:2] for x in l2]
    for a, b in zip(l1, l2):
        assert abs(a[2] - b[2]) < 1e-5
    assert set(i1.edges) == set(i2.edges)
    assert i1.ingest_dispatch_count == 1
    assert i2.ingest_dispatch_count > 1


def test_pod_ingest_one_distributed_dispatch_and_telemetry_free():
    """Jit counter: one coalesced mega-batch == ONE distributed dispatch
    (after warmup), and turning telemetry ON adds ZERO dispatches — the
    PR 6 serving guarantee extended to the write path."""
    idx = _pod_index(_mesh(4), fused=True)
    _ingest_batch(idx, prefix="w")             # warm/compile
    for enabled, prefix in ((True, "a"), (False, "b")):
        idx.telemetry.enabled = enabled
        calls = {"n": 0}
        orig = idx._ingest_dispatch

        def counting(fn, *a, __o=orig, **kw):
            calls["n"] += 1
            return __o(fn, *a, **kw)

        idx._ingest_dispatch = counting
        _ingest_batch(idx, prefix=prefix)
        idx._ingest_dispatch = orig
        assert calls["n"] == 1, (enabled, calls)
    idx.telemetry.enabled = True
    # the device-counter tail landed in the registry off the SAME readback
    assert idx.telemetry.counter_total("ingest.dedup_hits") > 0
    assert idx.telemetry.counter_total("ingest.links_accepted") > 0


def test_pod_ingest_overflow_reinsert_parity():
    """A tiny link-accept hint forces pool overflow: the overflowed edges
    are re-inserted host-side bit-identically (same edge set and weights
    as the hint=1.0 run), one pool-overflow counter bump."""
    i1 = _pod_index(_mesh(2), fused=True)
    i2 = _pod_index(_mesh(2), fused=True)
    batch = _clustered(10, 2)
    o1 = i1.ingest([f"f{i}" for i in range(10)], batch, "u", link_k=3,
                   link_gate=0.5, link_accept_hint=1.0)
    o2 = i2.ingest([f"f{i}" for i in range(10)], batch, "u", link_k=3,
                   link_gate=0.5, link_accept_hint=0.05)
    assert o1["links"] and o2["counters"]["overflow"]
    assert sorted(o1["links"]) == sorted(o2["links"])
    assert i1.link_pool_overflows == 0 and i2.link_pool_overflows == 1
    assert set(i1.edges) == set(i2.edges)


def test_pod_ingest_maintains_int8_shadow_incrementally():
    """With int8 serving on and a built shadow, the fused pod ingest
    updates the codes in-kernel (no dirty mark, codes equal a fresh
    requantize of the post-ingest master)."""
    from lazzaro_tpu.ops.quant import quantize_rows

    idx = _pod_index(_mesh(4), fused=True, int8_serving=True)
    idx._int8_shadow_for()
    _ingest_batch(idx)
    assert not idx._int8_dirty
    q8_ref, sc_ref = quantize_rows(idx.state.emb)
    np.testing.assert_array_equal(np.asarray(q8_ref)[:CAP],
                                  np.asarray(idx._int8_shadow[0])[:CAP])
    np.testing.assert_array_equal(np.asarray(sc_ref)[:CAP],
                                  np.asarray(idx._int8_shadow[1])[:CAP])


def test_pod_ingest_then_serve_roundtrip():
    """Rows written by the fused pod ingest serve through the fused pod
    retrieval path (the write and read programs share one arena)."""
    from lazzaro_tpu.serve import RetrievalRequest

    idx = _pod_index(_mesh(4), fused=True)
    _ingest_batch(idx)
    q = _clustered(10, 2)[0]
    res = idx.serve_requests([RetrievalRequest(query=q, tenant="u",
                                               k=3)])[0]
    assert res.ids and res.ids[0] == "f0"


def test_pod_warmup_ingest_leaves_corpus_unchanged():
    idx = _pod_index(_mesh(2), fused=True)
    before = set(idx.id_to_row)
    out = idx.warmup_ingest((4,))
    assert out and all(v > 0 for v in out.values())
    assert set(idx.id_to_row) == before
    key = 'kernel.warmup_ms{batch="4",path="ingest"}'
    assert idx.telemetry.timer_count("kernel.warmup_ms") >= 1
    assert any("ingest" in k for k in idx.telemetry.timers
               if k.startswith("kernel.warmup_ms"))
    del key


def test_mesh_memory_index_routes_sharded_and_matches_single_chip():
    """``MemoryIndex(mesh=...)`` ingest_batch_dedup runs the distributed
    program (one ingest dispatch) and its dedup verdicts, edges, and
    arena columns match the single-chip index on the same facts."""
    def run(mesh):
        rng = np.random.default_rng(0)
        idx = MemoryIndex(dim=D, capacity=CAP, edge_capacity=511,
                          dtype=np.float32, mesh=mesh)
        pre = rng.standard_normal((20, D)).astype(np.float32)
        idx.add([f"p{i}" for i in range(20)], pre, [0.5] * 20, [0.0] * 20,
                ["semantic"] * 20, ["a"] * 20, "u")
        batch = rng.standard_normal((6, D)).astype(np.float32)
        batch[4] = (pre[2] / np.linalg.norm(pre[2])
                    + 0.02 * rng.standard_normal(D))
        pending = idx.ingest_batch_dedup(batch, [0.6] * 6, [0.0] * 6,
                                         ["semantic"] * 6, ["a"] * 6, "u",
                                         dedup_gate=0.95)
        ids = [None if pending["dup"][i] else f"f{i}" for i in range(6)]
        _, _, merges, chains = idx.commit_ingest_dedup(pending, ids)
        return idx, np.asarray(pending["dup"]), merges, chains

    i1, d1, m1, c1 = run(_mesh(4))
    assert i1.ingest_sharded and len(i1._ingest_sharded_cache) == 1
    assert i1.ingest_dispatch_count == 1
    i2, d2, m2, c2 = run(None)
    np.testing.assert_array_equal(d1, d2)
    assert m1 == m2 and c1 == c2
    assert set(i1.edge_slots) == set(i2.edge_slots)
    for col in ("emb", "salience", "alive", "tenant_id", "access_count"):
        np.testing.assert_array_equal(
            np.asarray(getattr(i1.state, col))[:CAP],
            np.asarray(getattr(i2.state, col))[:CAP], err_msg=col)


def test_mesh_memory_index_gspmd_fallback_still_works():
    """``ingest_sharded=False`` keeps the GSPMD-partitioned plain jit
    kernel as fallback — same verdicts, no sharded kernel built."""
    rng = np.random.default_rng(1)
    idx = MemoryIndex(dim=D, capacity=CAP, edge_capacity=511,
                      dtype=np.float32, mesh=_mesh(2),
                      ingest_sharded=False)
    idx.add(["p0", "p1"], rng.standard_normal((2, D)).astype(np.float32),
            [0.5] * 2, [0.0] * 2, ["semantic"] * 2, ["a"] * 2, "u")
    pending = idx.ingest_batch_dedup(
        rng.standard_normal((4, D)).astype(np.float32), [0.5] * 4,
        [0.0] * 4, ["semantic"] * 4, ["a"] * 4, "u", dedup_gate=0.95)
    idx.commit_ingest_dedup(pending, [f"f{i}" for i in range(4)])
    assert len(idx._ingest_sharded_cache) == 0
    assert len(idx) == 6


def test_single_chip_warmup_ingest():
    """``MemoryIndex.warmup_ingest`` populates the ingest jit caches via
    the real path, records kernel.warmup_ms{path="ingest"}, and leaves
    the live corpus untouched."""
    rng = np.random.default_rng(2)
    idx = MemoryIndex(dim=D, capacity=CAP, edge_capacity=511,
                      dtype=np.float32)
    idx.add(["p0"], rng.standard_normal((1, D)).astype(np.float32),
            [0.5], [0.0], ["semantic"], ["a"], "u")
    out = idx.warmup_ingest((4,))
    assert out and all(v > 0 for v in out.values())
    assert len(idx) == 1
    assert any(k.startswith("kernel.warmup_ms") and "ingest" in k
               for k in idx.telemetry.timers)


def test_coalesce_wait_span_recorded():
    """The per-mega-batch coalesce-wait span (ISSUE 9 satellite) lands in
    the registry when consolidation drains the coalescer."""
    from lazzaro_tpu.utils.batching import IngestCoalescer

    co = IngestCoalescer(max_facts=100, max_wait_s=60.0)
    co.add_conversation([{"content": "x"}], now=100.0)
    co.add_conversation([{"content": "y"}], now=101.0)
    assert co.oldest_age_s(103.0) == pytest.approx(3.0)
    co.drain()
    assert co.oldest_age_s(104.0) == 0.0


def test_pod_add_rides_fused_ingest_no_extras_spills():
    """ISSUE 18 satellite: with live online-IVF tables, an all-fresh pod
    ``add()`` routes through the fused ingest program — the in-kernel
    assignment lands the rows in member slots, so ``ivf.add_extras_spills``
    stays flat — while add() semantics are untouched: duplicate
    embeddings still get their own rows (nothing merges), no similarity
    edges insert, and re-adds keep the classic overwrite-in-place path."""
    from lazzaro_tpu.utils.telemetry import Telemetry

    tel = Telemetry()
    rng = np.random.default_rng(9)
    idx = ShardedMemoryIndex(_mesh(2), dim=D, capacity=CAP,
                             dtype=np.float32, telemetry=tel)
    emb = rng.standard_normal((40, D)).astype(np.float32)
    idx.add([f"s{i}" for i in range(40)], emb, "u")
    assert idx.ivf_build(nprobe=4)
    spills0 = tel.counter_total("ivf.add_extras_spills")
    ing0 = idx.ingest_dispatch_count
    edges0 = len(idx.edges)
    dup = rng.standard_normal((1, D)).astype(np.float32)
    batch = np.concatenate([dup, dup,
                            rng.standard_normal((4, D)).astype(np.float32)])
    rows = idx.add([f"f{i}" for i in range(6)], batch, "u")
    # happy path: fused write, zero extras spills, rows routed in-kernel
    assert idx.ingest_dispatch_count == ing0 + 1
    assert tel.counter_total("ivf.add_extras_spills") == spills0
    assert all(idx._ivf_routed[r] for r in rows)
    assert not idx._ivf_fresh
    # add() semantics intact: 6 distinct rows (the identical pair did NOT
    # merge), every id registered, and no edges appeared
    assert len(set(rows)) == 6
    assert all(idx.id_to_row[f"f{i}"] == r for i, r in enumerate(rows))
    assert len(idx.edges) == edges0
    # a re-add of an existing id keeps the classic overwrite path
    spills1 = tel.counter_total("ivf.add_extras_spills")
    again = idx.add(["f0"], rng.standard_normal((1, D)).astype(np.float32),
                    "u")
    assert again == [rows[0]]
    assert tel.counter_total("ivf.add_extras_spills") >= spills1
    # the new facts are servable
    ids, _ = idx.search(batch[2], "u")
    assert ids[0] == "f2"
