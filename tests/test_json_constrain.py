"""Constrained JSON decoding: automaton correctness + generation guarantees.

The pipeline-level claim under test: ``generate_json`` emits parseable JSON
from ANY weights (random init included), because logits are masked to the
grammar's legal next-byte set and budget exhaustion is repaired by the
shortest closing suffix.
"""

import json

import numpy as np
import pytest

from lazzaro_tpu.models.json_constrain import (
    JsonState, constrain_mask, validate_json_bytes)

VALID = [
    b'{}', b'[]', b'null', b'true', b'false', b'0', b'-0', b'42', b'-3.5',
    b'1e9', b'2.5E-3', b'""', b'"hi"', b'"\\n\\u00e9"',
    b'{"a": 1}', b'{"a": {"b": [1, 2, {"c": null}]}, "d": "x"}',
    b'[1, "two", false, null, [], {}]',
    b'  { "k" : [ 1 , 2 ] }  ',
    b'{"memories": [{"content": "works as engineer", "type": "semantic", '
    b'"salience": 0.8, "topic": "work"}]}',
]

INVALID = [
    b'', b'{', b'[1,', b'{"a"}', b'{"a":}', b'{,}', b'[,]', b'01', b'1.',
    b'-', b'+1', b'.5', b'"unterminated', b"'single'", b'{"a":1,}', b'[1 2]',
    b'nul', b'truefalse', b'{"a":1}}', b'[]]', b'1e', b'1e+', b'{"\\x":1}',
    b'tru\x65e',
]


def test_accepts_valid_documents():
    for doc in VALID:
        assert validate_json_bytes(doc), doc
        json.loads(doc.decode())           # sanity: stdlib parses it too


def test_rejects_invalid_documents():
    for doc in INVALID:
        assert not validate_json_bytes(doc), doc


def test_agrees_with_stdlib_on_random_fuzz():
    """Random byte strings over a JSON-ish alphabet: automaton accept ⇒
    json.loads accepts (no false positives — the safety direction)."""
    rng = np.random.RandomState(0)
    alphabet = b'{}[]",:.0123456789truefalsn\\ -eE+'
    agree = 0
    for _ in range(3000):
        n = rng.randint(1, 24)
        doc = bytes(alphabet[i] for i in rng.randint(0, len(alphabet), n))
        if validate_json_bytes(doc):
            json.loads(doc.decode())    # must not raise
            agree += 1
    assert agree > 0    # fuzz actually exercised the accept path


def test_force_object_pins_top_level():
    assert validate_json_bytes(b'{"a": 1}', force_object=True)
    assert not validate_json_bytes(b'[1]', force_object=True)
    assert not validate_json_bytes(b'"str"', force_object=True)


def test_closing_suffix_repairs_any_prefix():
    """Every legal prefix + closing_suffix parses with stdlib json."""
    prefixes = [
        b'', b'{', b'{"key', b'{"key"', b'{"key":', b'{"key": [1, 2',
        b'{"a": {"b": "unfinished str', b'{"a": "esc\\', b'{"a": "\\u0',
        b'{"a": -', b'{"a": 3.', b'{"a": 1e', b'{"a": tr', b'[',
        b'[1, {"x": [true, nu', b'{"a": 1', b'{"a": 1,', b'{"a": 1, "b"',
    ]
    for prefix in prefixes:
        st = JsonState(force_object=(prefix[:1] != b'['))
        for b in prefix:
            assert b in st.allowed(), (prefix, bytes([b]))
            st.feed(b)
        repaired = prefix + st.closing_suffix()
        json.loads(repaired.decode())   # must not raise
        if prefix[:1] != b'[':
            assert isinstance(json.loads(repaired.decode()), dict) or prefix == b''


def test_constrain_mask_shape_and_eos():
    st = JsonState(force_object=True)
    mask = constrain_mask(st, 512, eos_id=258)
    assert mask.shape == (512,)
    assert mask[ord('{')] and not mask[ord('[')] and not mask[258]
    for b in b'{"a": 1}':
        st.feed(b)
    mask = constrain_mask(st, 512, eos_id=258)
    assert mask[258]                       # document complete → EOS legal
    assert not mask[ord('{')]


@pytest.mark.parametrize("temperature", [0.0, 1.0])
def test_generate_json_always_parses_with_random_weights(temperature):
    from lazzaro_tpu.models.llm import LanguageModel, LMConfig

    lm = LanguageModel(LMConfig.tiny(), seed=0)
    for seed in range(3):
        out = lm.generate_json("Extract facts as JSON:", max_new_tokens=48,
                               temperature=temperature, seed=seed)
        doc = json.loads(out)              # must not raise
        assert isinstance(doc, dict)       # force_object default


def test_generate_json_top_level_number_not_truncated(monkeypatch):
    """force_object=False + a model that wants to emit '42' then EOS: the
    loop must not break after the first digit (a top-level number is `done`
    but still extendable)."""
    import jax.numpy as jnp
    from lazzaro_tpu.models.llm import ByteTokenizer, LanguageModel, LMConfig

    lm = LanguageModel(LMConfig.tiny(), seed=0)
    script = iter([ord("4"), ord("2"), ByteTokenizer.EOS])

    def fake_logits():
        v = np.full((1, lm.cfg.vocab_size), -1e9, np.float32)
        v[0, next(script)] = 0.0
        return jnp.asarray(v)

    monkeypatch.setattr(lm, "_prefill", lambda p, t, pos, c: (fake_logits(), c))
    monkeypatch.setattr(lm, "_decode_one", lambda p, t, pos, c: (fake_logits(), c))
    # host loop explicitly: the scripted-logits mocks hook the host-side
    # step functions, which the jitted device loop cannot see (its
    # semantics are pinned against the host loop in test_json_device.py)
    out = lm.generate_json("n:", max_new_tokens=8, force_object=False,
                           device_loop=False)
    assert out == "42"
    assert json.loads(out) == 42


def test_on_device_llm_json_response_format():
    from lazzaro_tpu.core.providers import OnDeviceLLM
    from lazzaro_tpu.models.llm import LanguageModel, LMConfig

    llm = OnDeviceLLM(LanguageModel(LMConfig.tiny(), seed=1),
                      max_new_tokens=32)
    out = llm.completion([{"role": "user", "content": "extract facts"}],
                         response_format={"type": "json_object"})
    assert isinstance(json.loads(out), dict)
    # Without the format flag, free-text generation still works.
    txt = llm.completion([{"role": "user", "content": "hi"}])
    assert isinstance(txt, str)


def test_generate_json_scaffold_prefix():
    # Schema-scaffolded decoding: the output must start with the literal
    # scaffold, remain valid JSON by construction, and carry the pinned key
    # even under random weights.
    import json
    from lazzaro_tpu.models.llm import LanguageModel, LMConfig

    lm = LanguageModel(LMConfig.tiny(), seed=0)
    scaffold = '{"memories": [{"content": "'
    doc = lm.generate_json("Extract.", max_new_tokens=24, scaffold=scaffold)
    assert doc.startswith(scaffold)
    parsed = json.loads(doc)
    assert isinstance(parsed["memories"], list) and parsed["memories"]
    assert isinstance(parsed["memories"][0].get("content"), str)


def test_generate_json_scaffold_rejects_invalid_prefix():
    import pytest
    from lazzaro_tpu.models.llm import LanguageModel, LMConfig

    lm = LanguageModel(LMConfig.tiny(), seed=0)
    with pytest.raises(ValueError, match="valid JSON prefix"):
        lm.generate_json("x", scaffold='{"a": }')
