"""Node/Edge defaults and MemorySystem init flags.

Mirrors reference tests/test_basic.py (SURVEY §4): dataclass defaults and
constructor flag plumbing, but against the TPU-native implementation and
with offline providers instead of patched openai modules.
"""

import time

from lazzaro_tpu.models.graph import Edge, Node


def test_node_defaults():
    node = Node(id="n1", content="hello")
    assert node.type == "semantic"
    assert node.salience == 0.5
    assert node.access_count == 0
    assert not node.is_super_node
    assert node.child_ids == []
    assert node.parent_id is None
    assert abs(node.timestamp - time.time()) < 5


def test_edge_defaults():
    edge = Edge(source="a", target="b")
    assert edge.weight == 0.5
    assert edge.edge_type == "relates_to"
    assert edge.co_occurrence == 1


def test_node_round_trip_filters_unknown_keys():
    d = Node(id="n1", content="x", salience=0.7).to_dict()
    d["unknown_future_field"] = 123
    node = Node.from_dict(d)
    assert node.id == "n1"
    assert node.salience == 0.7


def test_edge_round_trip():
    e = Edge(source="a", target="b", weight=0.9, edge_type="causes")
    e2 = Edge.from_dict({**e.to_dict(), "bogus": 1})
    assert e2.key == ("a", "b")
    assert e2.weight == 0.9
    assert e2.edge_type == "causes"


def test_memory_system_init_flags(tmp_db):
    from lazzaro_tpu import MemorySystem

    ms = MemorySystem(
        enable_sharding=False,
        enable_hierarchy=False,
        enable_caching=False,
        enable_async=False,
        max_buffer_size=7,
        db_dir=tmp_db,
        load_from_disk=False,
        verbose=False,
    )
    assert ms.enable_sharding is False
    assert ms.enable_hierarchy is False
    assert ms.query_cache is None
    assert ms.background_executor is None
    assert ms.max_buffer_size == 7
    assert ms.vector_store is ms.store  # back-compat alias
    ms.close()


def test_default_construction_enables_cache_and_async(tmp_db):
    from lazzaro_tpu import MemorySystem
    ms = MemorySystem(db_dir=tmp_db, load_from_disk=False, verbose=False)
    try:
        # config defaults say caching+async are on; the constructor must
        # honor them when the kwargs are left at None
        assert ms.query_cache is not None
        assert ms.background_executor is not None
    finally:
        ms.close()


def test_edge_placement_cache_o1_and_self_healing(tmp_db):
    """Edge bookkeeping is O(1) via the edge_key→shard map; entries are
    validated on read so direct shard mutation only costs a repair scan
    (verdict r2 weak #8)."""
    from lazzaro_tpu.core.memory_system import MemorySystem

    ms = MemorySystem(enable_async=False, db_dir=tmp_db, verbose=False,
                      load_from_disk=False)
    for i, sk in enumerate(["work", "personal", "health"]):
        n = Node(id=f"n{i}", content=f"content {i}", shard_key=sk)
        ms._get_or_create_shard(sk).add_node(n)
    ms._add_edges_batch([Edge(source="n0", target="n1", weight=0.9)])
    assert ms._edge_shard[("n0", "n1")] == "work"
    assert ms._find_edge(("n0", "n1")).weight == 0.9

    # Reinforce goes to the cached shard, not a new one.
    ms._add_edges_batch([Edge(source="n0", target="n1", weight=0.9)])
    assert len(ms.shards["work"].edges) == 1
    assert ms.shards["work"].edges[("n0", "n1")].co_occurrence == 2

    # Out-of-band deletion (reference-style direct mutation): the stale
    # entry self-heals instead of returning a dead edge.
    del ms.shards["work"].edges[("n0", "n1")]
    assert ms._find_edge(("n0", "n1")) is None
    assert ("n0", "n1") not in ms._edge_shard
    ms.close()


def test_fetch_packed_bitcast_round_trip():
    """One-readback packed fetch (utils/batching): ints bitcast through f32
    must round-trip bit-exactly, including negatives/sentinels and extreme
    values; floats come back untouched."""
    import numpy as np
    import jax.numpy as jnp
    from lazzaro_tpu.utils.batching import fetch_packed

    f = np.array([[1.5, -2.25], [3.0, float("-1e30")]], np.float32)
    i = np.array([[-1, 2147483647], [-2147483648, 0]], np.int32)
    f2 = np.array([[0.0, 1e-38], [np.pi, -0.0]], np.float32)
    got_f, got_i, got_f2 = fetch_packed(jnp.asarray(f), jnp.asarray(i),
                                        jnp.asarray(f2))
    np.testing.assert_array_equal(got_f, f)
    np.testing.assert_array_equal(got_i, i)
    np.testing.assert_array_equal(got_f2, f2)
    assert got_i.dtype == np.int32 and got_f.dtype == np.float32
