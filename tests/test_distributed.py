"""Two-process `jax.distributed` smoke test (verdict r2 missing #3).

Spawns a real coordinator + worker subprocess pair on CPU (4 virtual
devices each → an 8-device global mesh spanning two OS processes), builds
``make_hybrid_mesh``, and runs the sharded top-k collective plus a
data-parallel encoder train step — the multi-host path beyond
single-process SPMD (`parallel/mesh.py:44-84`), executed rather than
merely documented. The reference's closest analog is the two-instance
store-sync test (test_v03_migration.py:84-108); this is the TPU-pod
equivalent.
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.slow

WORKER = Path(__file__).resolve().parent / "distributed_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_mesh_topk_and_train():
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)               # worker sets its own device count
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(WORKER.parents[1]) + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(pid), "2", str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("distributed pair timed out:\n" + "\n---\n".join(
            p.stdout.read() if p.stdout else "" for p in procs))
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out}"
        assert "DIST_OK" in out, f"process {pid} output:\n{out}"
    # Both processes computed the SAME replicated loss (true SPMD).
    l0 = [l for l in outs[0].splitlines() if "DIST_OK" in l][0].split("loss2=")[1]
    l1 = [l for l in outs[1].splitlines() if "DIST_OK" in l][0].split("loss2=")[1]
    assert l0 == l1
