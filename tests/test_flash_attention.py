"""Pallas flash attention vs the einsum reference (interpret mode on CPU)."""

# Compile-heavy (multi-second XLA compiles / 100k-row arenas): the
# default lane must stay inside a driver window; run the full lane
# with no -m filter for round gates.
pytestmark = __import__("pytest").mark.slow

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lazzaro_tpu.ops.flash_attention import flash_attention, _reference_gqa


def _rand(shape, seed):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape), jnp.float32)


@pytest.mark.parametrize("B,T,H,Hkv,D", [
    (2, 64, 4, 2, 32),     # GQA, block-aligned
    (1, 37, 4, 4, 16),     # MHA, odd length → internal padding
    (1, 8, 2, 1, 8),       # tiny, extreme GQA
])
def test_matches_reference(B, T, H, Hkv, D):
    q = _rand((B, T, H, D), 0)
    k = _rand((B, T, Hkv, D), 1)
    v = _rand((B, T, Hkv, D), 2)
    out = flash_attention(q, k, v, blk_q=16, blk_k=16)
    ref = _reference_gqa(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("T,S", [(8, 32), (13, 29)])
def test_chunked_prefill_end_aligned(T, S):
    """S > T: q are the LAST T positions of an S-token context."""
    q = _rand((1, T, 2, 16), 10)
    k = _rand((1, S, 2, 16), 11)
    v = _rand((1, S, 2, 16), 12)
    out = flash_attention(q, k, v, blk_q=8, blk_k=8)
    ref = _reference_gqa(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_kv_shorter_than_q_rejected():
    q = _rand((1, 16, 2, 8), 13)
    k = _rand((1, 8, 2, 8), 14)
    with pytest.raises(ValueError):
        flash_attention(q, k, k)


def test_causality():
    """Perturbing a future token must not change earlier outputs."""
    q = _rand((1, 32, 2, 16), 3)
    k = _rand((1, 32, 2, 16), 4)
    v = _rand((1, 32, 2, 16), 5)
    base = flash_attention(q, k, v, blk_q=8, blk_k=8)
    k2 = k.at[:, 20:].add(3.0)
    v2 = v.at[:, 20:].add(-2.0)
    pert = flash_attention(q, k2, v2, blk_q=8, blk_k=8)
    np.testing.assert_allclose(np.asarray(base[:, :20]),
                               np.asarray(pert[:, :20]), atol=1e-6)
    assert not np.allclose(np.asarray(base[:, 20:]), np.asarray(pert[:, 20:]))


def test_gradients_match_reference():
    q = _rand((1, 16, 2, 8), 6)
    k = _rand((1, 16, 2, 8), 7)
    v = _rand((1, 16, 2, 8), 8)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, blk_q=8, blk_k=8) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_reference_gqa(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_decoder_flash_equals_xla():
    """Same params, attn_impl=flash vs xla → same logits."""
    from lazzaro_tpu.models.llm import Decoder, LMConfig
    import dataclasses

    cfg_x = LMConfig.tiny()
    cfg_f = dataclasses.replace(cfg_x, attn_impl="flash")
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 250, (2, 24)),
                         jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(24)[None], (2, 24)).astype(jnp.int32)
    params = Decoder(cfg_x).init(jax.random.PRNGKey(0), tokens, positions)["params"]
    lx, _ = Decoder(cfg_x).apply({"params": params}, tokens, positions)
    lf, _ = Decoder(cfg_f).apply({"params": params}, tokens, positions)
    np.testing.assert_allclose(np.asarray(lx), np.asarray(lf),
                               atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("B,T,S,H,Hkv,D,blk", [
    (2, 16, 16, 4, 2, 8, 8),     # GQA rep=2, self-attention
    (1, 8, 24, 4, 1, 8, 8),      # chunked prefill S>T, rep=4 (MQA)
    (1, 13, 21, 2, 2, 8, 8),     # ragged lengths: internal padding active
])
def test_fused_backward_matches_reference(B, T, S, H, Hkv, D, blk):
    """The Pallas backward (LSE-recompute, no [T,S] HBM tensor) must agree
    with autodiff through the reference einsum on every layout: GQA head
    groups, end-aligned prefill, and padded (non-block-multiple) lengths."""
    q = _rand((B, T, H, D), 1)
    k = _rand((B, S, Hkv, D), 2)
    v = _rand((B, S, Hkv, D), 3)
    g = _rand((B, T, H, D), 4)          # non-trivial upstream cotangent

    def f_flash(q, k, v):
        return jnp.vdot(flash_attention(q, k, v, blk_q=blk, blk_k=blk), g)

    def f_ref(q, k, v):
        return jnp.vdot(_reference_gqa(q, k, v), g)

    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=2e-4)


def test_train_step_flash_matches_xla_gradients():
    """One full decoder train step under attn_impl=flash vs xla: identical
    loss and updated params — the fused VJP is a drop-in for training."""
    import dataclasses
    import optax
    from lazzaro_tpu.models.llm import Decoder, LMConfig, make_train_step

    cfg_x = dataclasses.replace(LMConfig.tiny(), max_seq=32)
    cfg_f = dataclasses.replace(cfg_x, attn_impl="flash")
    tokens = jnp.asarray(np.random.RandomState(3).randint(0, 250, (2, 24)),
                         jnp.int32)
    mask = jnp.ones_like(tokens)
    params = Decoder(cfg_x).init(
        jax.random.PRNGKey(0), tokens,
        jnp.broadcast_to(jnp.arange(24)[None], (2, 24)))["params"]
    opt = optax.sgd(1e-2)
    outs = {}
    for name, cfg in (("xla", cfg_x), ("flash", cfg_f)):
        step = make_train_step(cfg, opt)      # donates params: copy per run
        p0 = jax.tree_util.tree_map(jnp.copy, params)
        p, _, loss = step(p0, opt.init(p0), tokens, mask)
        outs[name] = (p, float(loss))
    assert outs["xla"][1] == pytest.approx(outs["flash"][1], abs=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                atol=5e-5, rtol=5e-5),
        outs["xla"][0], outs["flash"][0])
