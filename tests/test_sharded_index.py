"""ShardedMemoryIndex on the 8-device mesh: placement, search, isolation."""

import numpy as np
import pytest

from lazzaro_tpu.parallel.index import ShardedMemoryIndex
from lazzaro_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(("data",), (8,))


def basis(dim, i):
    v = np.zeros(dim, np.float32)
    v[i % dim] = 1.0
    return v


def test_add_search_roundtrip(mesh):
    idx = ShardedMemoryIndex(mesh, dim=32, capacity=256, dtype=np.float32)
    ids = [f"n{i}" for i in range(10)]
    embs = np.stack([basis(32, i) for i in range(10)])
    idx.add(ids, embs, "alice")
    got, scores = idx.search(basis(32, 4), "alice")
    assert got[0] == "n4"
    assert scores[0] == pytest.approx(1.0, abs=1e-5)


def test_tenant_affinity_placement(mesh):
    idx = ShardedMemoryIndex(mesh, dim=16, capacity=256, tenant_affinity=True)
    idx.add(["a1", "a2"], np.stack([basis(16, 1), basis(16, 2)]), "alice")
    idx.add(["b1"], basis(16, 3).reshape(1, -1), "bob")
    parts_a = {idx.partition_of("a1"), idx.partition_of("a2")}
    assert len(parts_a) == 1  # same home partition
    # bob may or may not share alice's partition (hash), but placement is stable
    assert idx.partition_of("b1") == abs(hash("bob")) % 8


def test_tenant_isolation_and_delete(mesh):
    idx = ShardedMemoryIndex(mesh, dim=16, capacity=256)
    idx.add(["a"], basis(16, 5).reshape(1, -1), "u1")
    idx.add(["b"], basis(16, 5).reshape(1, -1), "u2")
    got, _ = idx.search(basis(16, 5), "u1")
    assert got == ["a"]
    idx.delete(["a"])
    got, _ = idx.search(basis(16, 5), "u1")
    assert got == []


def test_spill_when_home_partition_full(mesh):
    idx = ShardedMemoryIndex(mesh, dim=16, capacity=64)  # 8 rows per partition
    n = 20  # > one partition
    ids = [f"x{i}" for i in range(n)]
    embs = np.stack([basis(16, i) for i in range(n)])
    idx.add(ids, embs, "carol")
    # everything searchable despite spilling across partitions
    got, _ = idx.search(basis(16, 13), "carol")
    assert "x13" in got


def test_decay_tenant_scoped(mesh):
    idx = ShardedMemoryIndex(mesh, dim=16, capacity=64)
    idx.add(["a"], basis(16, 0).reshape(1, -1), "u1", saliences=[0.9])
    idx.add(["b"], basis(16, 1).reshape(1, -1), "u2", saliences=[0.9])
    idx.decay("u1", rate=0.01, floor=0.2)
    sal = np.asarray(idx.salience)
    assert sal[idx.id_to_row["a"]] == pytest.approx(0.893, abs=1e-5)
    assert sal[idx.id_to_row["b"]] == pytest.approx(0.9, abs=1e-6)


def test_pallas_topk_interpret():
    import jax.numpy as jnp
    from lazzaro_tpu.ops.pallas_topk import pallas_masked_topk
    N, d, Q, K = 4096 * 2, 128, 8, 10
    rng = np.random.RandomState(3)
    emb = rng.randn(N, d).astype(np.float32)
    emb /= np.linalg.norm(emb, axis=1, keepdims=True)
    madd = np.zeros(N, np.float32)
    madd[::5] = -1e30
    qs = rng.randn(Q, d).astype(np.float32)
    s, i = pallas_masked_topk(jnp.asarray(emb), jnp.asarray(madd),
                              jnp.asarray(qs), k=K, interpret=True)
    i = np.asarray(i)
    ref = qs @ emb.T + madd[None, :]
    for r in range(Q):
        assert set(i[r]) == set(np.argsort(-ref[r])[:K])
