"""ArrowStore: round-trips, empty-list delete-all parity, versioning."""

import pytest

from lazzaro_tpu.core.store import ArrowStore


@pytest.fixture()
def store(tmp_db):
    s = ArrowStore(tmp_db)
    yield s
    s.close()


def make_node(i, dim=4):
    emb = [0.0] * dim
    emb[i % dim] = 1.0
    return {"id": f"node_{i}", "content": f"content {i}", "embedding": emb,
            "type": "semantic", "salience": 0.5, "shard_key": "default",
            "child_ids": [], "metadata": {"k": i}}


def test_node_round_trip(store):
    store.add_nodes([make_node(1), make_node(2)], user_id="u1")
    rows = store.get_nodes(user_id="u1")
    assert {r["id"] for r in rows} == {"node_1", "node_2"}
    r1 = next(r for r in rows if r["id"] == "node_1")
    assert r1["content"] == "content 1"
    assert r1["metadata"] == {"k": 1}
    assert r1["child_ids"] == []


def test_add_nodes_upserts(store):
    store.add_nodes([make_node(1)], user_id="u1")
    updated = make_node(1)
    updated["content"] = "updated"
    store.add_nodes([updated], user_id="u1")
    rows = store.get_nodes(user_id="u1")
    assert len(rows) == 1
    assert rows[0]["content"] == "updated"


def test_user_isolation(store):
    store.add_nodes([make_node(1)], user_id="u1")
    store.add_nodes([make_node(2)], user_id="u2")
    assert {r["id"] for r in store.get_nodes(user_id="u1")} == {"node_1"}
    assert {r["id"] for r in store.get_nodes(user_id="u2")} == {"node_2"}
    assert store.get_all_users() == ["u1", "u2"]


def test_search_nodes_brute_force(store):
    store.add_nodes([make_node(0), make_node(1)], user_id="u1")
    ids = store.search_nodes([1.0, 0.0, 0.0, 0.0], user_id="u1", limit=1)
    assert ids == ["node_0"]


def test_delete_empty_list_deletes_all(store):
    # parity quirk: empty id list ⇒ delete ALL user rows (vector_store.py:143-145)
    store.add_nodes([make_node(1), make_node(2)], user_id="u1")
    store.add_nodes([make_node(3)], user_id="u2")
    store.delete_nodes([], user_id="u1")
    assert store.get_nodes(user_id="u1") == []
    assert len(store.get_nodes(user_id="u2")) == 1


def test_edges_round_trip_typed_ids(store):
    store.add_edges([
        {"source": "a", "target": "b", "weight": 0.7, "edge_type": "relates_to"},
        {"source": "a", "target": "b", "weight": 0.4, "edge_type": "causes"},
    ], user_id="u1")
    rows = store.get_edges(user_id="u1")
    # typed parallel edges must not collide (reference id='src_tgt' collides)
    assert len(rows) == 2


def test_profile_round_trip(store):
    store.save_profile({"data": {"preferences": "tea"}}, user_id="u1")
    assert store.load_profile(user_id="u1") == {"data": {"preferences": "tea"}}
    assert store.load_profile(user_id="nobody") is None


def test_version_bumps_on_every_write(store):
    v0 = store.get_latest_version()
    store.add_nodes([make_node(1)], user_id="u1")
    v1 = store.get_latest_version()
    store.save_profile({"x": 1}, user_id="u1")
    v2 = store.get_latest_version()
    assert v0 < v1 < v2
