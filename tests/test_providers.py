"""Provider injection end-to-end through chat (reference test_providers.py
pattern) + the in-tree offline providers."""

import json

from lazzaro_tpu import MemorySystem
from lazzaro_tpu.core.providers import HashingEmbedder, HeuristicLLM

from tests.fakes import MockEmbedder, MockLLM


def make_ms(tmp_db, **kw):
    defaults = dict(
        enable_async=False,
        load_from_disk=False,
        db_dir=tmp_db,
        verbose=False,
    )
    defaults.update(kw)
    return MemorySystem(**defaults)


def test_injected_providers_drive_chat(tmp_db):
    llm = MockLLM(response="Hello from mock!")
    ms = make_ms(tmp_db, llm_provider=llm, embedding_provider=MockEmbedder())
    ms.start_conversation()
    out = ms.chat("Hi there")
    assert out == "Hello from mock!"
    assert len(llm.calls) == 1
    roles = [m["role"] for m in llm.calls[0]]
    assert roles[0] == "system"
    assert {"role": "user", "content": "Hi there"} in llm.calls[0]
    ms.close()


def test_default_providers_are_offline(tmp_db):
    ms = make_ms(tmp_db)
    assert isinstance(ms.llm, HeuristicLLM)
    assert isinstance(ms.embedder, HashingEmbedder)
    ms.close()


def test_hashing_embedder_similarity_properties():
    e = HashingEmbedder(dim=128)
    a = e.embed("the user loves python programming")
    b = e.embed("the user loves python programming")
    c = e.embed("completely unrelated gardening topic here")
    import numpy as np
    assert np.allclose(a, b)
    sim_dup = float(np.dot(a, b))
    sim_diff = float(np.dot(a, c))
    assert sim_dup > 0.99
    assert sim_diff < 0.5


def test_heuristic_llm_fact_extraction():
    llm = HeuristicLLM()
    payload = json.dumps([
        {"content": "I work on a big project. I love hiking with family.",
         "type": "episodic", "salience": 0.7},
    ])
    out = llm.completion([
        {"role": "system", "content": "Extract distinct, atomic facts from this conversation."},
        {"role": "user", "content": payload},
    ])
    data = json.loads(out)
    contents = [m["content"] for m in data["memories"]]
    assert any("project" in c for c in contents)
    topics = {m["topic"] for m in data["memories"]}
    assert "work" in topics
    assert "personal" in topics


def test_chat_stream_yields_info_then_tokens(tmp_db):
    ms = make_ms(tmp_db, llm_provider=MockLLM(response="streamed response"),
                 embedding_provider=MockEmbedder())
    ms.start_conversation()
    events = list(ms.chat_stream("tell me something"))
    kinds = [e["type"] for e in events]
    assert "info" in kinds
    assert "token" in kinds
    text = "".join(e["content"] for e in events if e["type"] == "token")
    assert text == "streamed response"
    ms.close()
