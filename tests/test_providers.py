"""Provider injection end-to-end through chat (reference test_providers.py
pattern) + the in-tree offline providers."""

import json

from lazzaro_tpu import MemorySystem
from lazzaro_tpu.core.providers import HashingEmbedder, HeuristicLLM

from tests.fakes import MockEmbedder, MockLLM


def make_ms(tmp_db, **kw):
    defaults = dict(
        enable_async=False,
        load_from_disk=False,
        db_dir=tmp_db,
        verbose=False,
    )
    defaults.update(kw)
    return MemorySystem(**defaults)


def test_injected_providers_drive_chat(tmp_db):
    llm = MockLLM(response="Hello from mock!")
    ms = make_ms(tmp_db, llm_provider=llm, embedding_provider=MockEmbedder())
    ms.start_conversation()
    out = ms.chat("Hi there")
    assert out == "Hello from mock!"
    assert len(llm.calls) == 1
    roles = [m["role"] for m in llm.calls[0]]
    assert roles[0] == "system"
    assert {"role": "user", "content": "Hi there"} in llm.calls[0]
    ms.close()


def test_default_providers_are_offline(tmp_db):
    ms = make_ms(tmp_db)
    assert isinstance(ms.llm, HeuristicLLM)
    assert isinstance(ms.embedder, HashingEmbedder)
    ms.close()


def test_hashing_embedder_similarity_properties():
    e = HashingEmbedder(dim=128)
    a = e.embed("the user loves python programming")
    b = e.embed("the user loves python programming")
    c = e.embed("completely unrelated gardening topic here")
    import numpy as np
    assert np.allclose(a, b)
    sim_dup = float(np.dot(a, b))
    sim_diff = float(np.dot(a, c))
    assert sim_dup > 0.99
    assert sim_diff < 0.5


def test_heuristic_llm_fact_extraction():
    llm = HeuristicLLM()
    payload = json.dumps([
        {"content": "I work on a big project. I love hiking with family.",
         "type": "episodic", "salience": 0.7},
    ])
    out = llm.completion([
        {"role": "system", "content": "Extract distinct, atomic facts from this conversation."},
        {"role": "user", "content": payload},
    ])
    data = json.loads(out)
    contents = [m["content"] for m in data["memories"]]
    assert any("project" in c for c in contents)
    topics = {m["topic"] for m in data["memories"]}
    assert "work" in topics
    assert "personal" in topics


def test_chat_stream_yields_info_then_tokens(tmp_db):
    ms = make_ms(tmp_db, llm_provider=MockLLM(response="streamed response"),
                 embedding_provider=MockEmbedder())
    ms.start_conversation()
    events = list(ms.chat_stream("tell me something"))
    kinds = [e["type"] for e in events]
    assert "info" in kinds
    assert "token" in kinds
    text = "".join(e["content"] for e in events if e["type"] == "token")
    assert text == "streamed response"
    ms.close()


def test_ondevice_llm_json_mode_with_subword_tokenizer():
    """json_object mode with an HF/subword tokenizer must fall back to
    free-text + JSON extraction instead of crashing on the byte-grammar
    requirement (advisor r1: providers.py:215)."""
    from lazzaro_tpu.core.providers import OnDeviceLLM, _extract_json_object

    class SubwordTok:          # not a ByteTokenizer
        eos_id = 2

    class StubLM:
        tokenizer = SubwordTok()

        def generate(self, prompt, max_new_tokens=128, temperature=0.0):
            return 'Sure thing!\n```json\n{"memories": [{"a": 1}]}\n```\ndone'

        def generate_json(self, *a, **k):
            raise ValueError("generate_json requires the byte tokenizer")

    llm = OnDeviceLLM(lm=StubLM())
    out = llm.completion([{"role": "user", "content": "extract"}],
                         response_format={"type": "json_object"})
    assert json.loads(out) == {"memories": [{"a": 1}]}

    # Extractor edge cases: bare object amid prose, nested braces in strings.
    assert json.loads(_extract_json_object('noise {"k": "a}b{c"} tail')) == \
        {"k": "a}b{c"}
    assert _extract_json_object("no json here") == "no json here"


def test_extract_json_skips_non_json_fence():
    from lazzaro_tpu.core.providers import _extract_json_object
    out = _extract_json_object('```\npseudo code\n```\n{"memories": [1]}')
    assert json.loads(out) == {"memories": [1]}


def test_extract_json_prefers_parseable_block():
    from lazzaro_tpu.core.providers import _extract_json_object
    # Pseudo-code fence WITH braces must not eat the trailing real object.
    out = _extract_json_object('```\nif x { return y }\n```\n{"memories": [1]}')
    assert json.loads(out) == {"memories": [1]}
    # Top-level arrays extract whole, not their first inner object.
    out = _extract_json_object('here: [{"a": 1}, {"b": 2}] done')
    assert json.loads(out) == [{"a": 1}, {"b": 2}]


def test_profile_extraction_survives_array_response(tmp_db):
    class ArrayLLM:
        def completion(self, messages, response_format=None):
            return '["preferences", "not a dict"]'

    ms = MemorySystem(enable_async=False, db_dir=tmp_db, verbose=False,
                      load_from_disk=False, llm_provider=ArrayLLM())
    out = ms._extract_profile_from_contents(["likes climbing"])
    assert "Failed" in out
    ms.close()
