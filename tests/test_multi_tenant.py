"""Multi-tenant at the BASELINE configs[1] cardinality (r4 verdict #6).

The reference partitions tenants with a LanceDB BTREE on user_id
(vector_store.py:55); here tenancy is a first-class arena column
(core/state.py tenant_id) masked inside every kernel. These tests push
the machinery to 1,000 tenants and verify what the column must
guarantee: search isolation (also batched), per-tenant eviction, decay
scoped to one tenant, and the system surface (switch_user /
get_all_users) at high user cardinality.
"""

# 1k tenants × 100 rows: minutes, not seconds — full-lane only.
pytestmark = __import__("pytest").mark.slow

import numpy as np

from lazzaro_tpu.core.index import MemoryIndex

N_TENANTS = 1000
ROWS_PER_TENANT = 100
DIM = 64


def _build_index():
    rng = np.random.default_rng(0)
    idx = MemoryIndex(dim=DIM, capacity=N_TENANTS * ROWS_PER_TENANT + 64,
                      edge_capacity=1024)
    for t in range(N_TENANTS):
        emb = rng.standard_normal((ROWS_PER_TENANT, DIM)).astype(np.float32)
        emb /= np.linalg.norm(emb, axis=1, keepdims=True)
        ids = [f"t{t}:m{i}" for i in range(ROWS_PER_TENANT)]
        idx.add(ids, emb, [0.5] * ROWS_PER_TENANT, [0.0] * ROWS_PER_TENANT,
                ["semantic"] * ROWS_PER_TENANT, ["default"] * ROWS_PER_TENANT,
                f"user{t}")
    return idx


def test_thousand_tenant_isolation_eviction_decay():
    idx = _build_index()
    assert len(idx._tenants) == N_TENANTS
    rng = np.random.default_rng(1)

    # search isolation: a query NEVER crosses its tenant mask — sample 25
    # tenants, query with another tenant's exact vector
    sample = rng.integers(0, N_TENANTS, size=25)
    import time
    lat = []
    for t in sample.tolist():
        other = (t + 1) % N_TENANTS
        q = np.asarray(
            idx.state.emb[idx.id_to_row[f"t{other}:m0"]], np.float32)
        t0 = time.perf_counter()
        ids, _ = idx.search(q, f"user{t}", k=5)
        lat.append((time.perf_counter() - t0) * 1e3)
        assert ids and all(i.startswith(f"t{t}:") for i in ids)
    p50 = float(np.percentile(lat, 50))
    assert p50 < 5000          # sanity ceiling; the bench records the number

    # batched search stays inside the tenant too
    qs = np.asarray(
        idx.state.emb[np.asarray([idx.id_to_row[f"t7:m{i}"]
                                  for i in range(8)])], np.float32)
    for ids, _ in idx.search_batch(qs, "user7", k=3):
        assert ids and all(i.startswith("t7:") for i in ids)

    # per-tenant eviction candidates come only from that tenant
    for t in sample[:5].tolist():
        cands = idx.evict_candidates(f"user{t}", k=7)
        assert cands and all(nid.startswith(f"t{t}:") for nid, _ in cands)

    # decay is tenant-scoped: user3's saliences drop, user4's are untouched
    r3 = [idx.id_to_row[f"t3:m{i}"] for i in range(5)]
    r4 = [idx.id_to_row[f"t4:m{i}"] for i in range(5)]
    before = np.asarray(idx.state.salience)
    idx.decay("user3", rate=0.1)
    after = np.asarray(idx.state.salience)
    assert (after[r3] < before[r3]).all()
    np.testing.assert_array_equal(after[r4], before[r4])


def test_system_thousand_users_switch_and_enumerate(tmp_path):
    """switch_user / get_all_users at 1k-user cardinality: every user's
    graph is isolated, enumeration sees everyone, and switching back
    restores a user's memories from the store."""
    from lazzaro_tpu.config import MemoryConfig
    from lazzaro_tpu.core.memory_system import MemorySystem

    n_users = 1000
    ms = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db"),
                      verbose=False, load_from_disk=False,
                      config=MemoryConfig(journal=False))
    first = ms.user_id
    for u in range(n_users):
        ms.switch_user(f"user{u}")
        ms.start_conversation()
        ms.add_to_short_term(f"user {u} owns artifact number {u}",
                             "semantic", 0.8)
        ms.end_conversation()
    users = ms.get_all_users()
    assert len([u for u in users if u.startswith("user")]) == n_users

    # spot-check isolation + restore-on-switch for a few users
    for u in (0, 499, 999):
        ms.switch_user(f"user{u}")
        hits = ms.search_memories(f"artifact number {u}")
        assert hits, f"user{u} lost their graph"
        assert all(f"user {u} " in n.content for n in hits)
    ms.switch_user(first)
    ms.close()
