"""BertEncoder numerics vs a real ``transformers`` BertModel (random-init,
built locally — zero egress) and the ``from_hf`` weight mapping."""

# Compile-heavy (multi-second XLA compiles / 100k-row arenas): the
# default lane must stay inside a driver window; run the full lane
# with no -m filter for round gates.
pytestmark = __import__("pytest").mark.slow

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp

from lazzaro_tpu.models.encoder import (
    BertEncoder, EncoderConfig, TextEncoder, bert_params_from_hf)


@pytest.fixture(scope="module")
def hf_model():
    cfg = transformers.BertConfig(
        vocab_size=100, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, hidden_act="gelu")
    torch.manual_seed(0)
    model = transformers.BertModel(cfg)
    model.eval()
    return model


def _our_cfg(hf_model, max_len=16):
    hc = hf_model.config
    return EncoderConfig(vocab_size=hc.vocab_size, hidden=hc.hidden_size,
                         layers=hc.num_hidden_layers,
                         heads=hc.num_attention_heads,
                         mlp_dim=hc.intermediate_size, max_len=max_len,
                         dtype="float32", arch="bert", pooling="cls")


def test_hidden_states_match_hf(hf_model):
    cfg = _our_cfg(hf_model)
    params = bert_params_from_hf(hf_model, cfg)
    rng = np.random.RandomState(0)
    # Token ids avoid 0 (our PAD); attention_mask all ones on the HF side.
    ids = rng.randint(1, 100, (3, 16))
    with torch.no_grad():
        ref = hf_model(input_ids=torch.tensor(ids),
                       attention_mask=torch.ones(3, 16, dtype=torch.long)
                       ).last_hidden_state.numpy()
    ours = BertEncoder(cfg).apply({"params": params}, jnp.asarray(ids),
                                  return_hidden=True)
    np.testing.assert_allclose(np.asarray(ours), ref, atol=2e-5, rtol=2e-5)


def test_hidden_states_match_hf_with_padding(hf_model):
    cfg = _our_cfg(hf_model)
    params = bert_params_from_hf(hf_model, cfg)
    rng = np.random.RandomState(1)
    ids = rng.randint(1, 100, (2, 16))
    ids[0, 10:] = 0                                # our PAD == HF pad id 0
    ids[1, 13:] = 0
    mask = (ids != 0).astype(np.int64)
    with torch.no_grad():
        ref = hf_model(input_ids=torch.tensor(ids),
                       attention_mask=torch.tensor(mask)
                       ).last_hidden_state.numpy()
    ours = np.asarray(BertEncoder(cfg).apply(
        {"params": params}, jnp.asarray(ids), return_hidden=True))
    # Compare only real (unpadded) positions; padded rows are don't-care.
    for b in range(2):
        n = int(mask[b].sum())
        np.testing.assert_allclose(ours[b, :n], ref[b, :n],
                                   atol=2e-5, rtol=2e-5)


def test_from_hf_cls_pooling_matches_manual(hf_model):
    enc = TextEncoder.from_hf(hf_model, max_len=16)
    rng = np.random.RandomState(2)
    ids = rng.randint(1, 100, (2, 16))
    with torch.no_grad():
        h = hf_model(input_ids=torch.tensor(ids),
                     attention_mask=torch.ones(2, 16, dtype=torch.long)
                     ).last_hidden_state.numpy()
    cls = h[:, 0]
    ref = cls / np.linalg.norm(cls, axis=-1, keepdims=True)
    ours = np.asarray(enc.model.apply(enc.params, jnp.asarray(ids)))
    np.testing.assert_allclose(ours, ref, atol=2e-5, rtol=2e-5)


def test_from_hf_with_vocab_file_matches_full_hf_pipeline(hf_model, tmp_path):
    """from_hf(vocab_file=...) reproduces the COMPLETE HF pipeline — real
    WordPiece ids + BertModel forward + CLS pooling — from just vocab.txt."""
    vocab = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "the", "quick", "fox",
             "jump", "##s", "hello", "world", "data", "engineer", "."]
    vocab += [f"tok{i}" for i in range(100 - len(vocab))]   # pad to hf vocab_size
    vf = tmp_path / "vocab.txt"
    vf.write_text("\n".join(vocab) + "\n", encoding="utf-8")

    enc = TextEncoder.from_hf(hf_model, max_len=16, vocab_file=str(vf))
    texts = ["the quick fox jumps.", "hello world", "unknownword data engineer"]
    ours = enc.encode_batch(texts)

    hf_tok = transformers.BertTokenizer(str(vf), do_lower_case=True)
    batch = hf_tok(texts, padding="max_length", truncation=True,
                   max_length=16, return_tensors="pt")
    with torch.no_grad():
        h = hf_model(input_ids=batch["input_ids"],
                     attention_mask=batch["attention_mask"]
                     ).last_hidden_state.numpy()
    cls = h[:, 0]
    ref = cls / np.linalg.norm(cls, axis=-1, keepdims=True)
    np.testing.assert_allclose(ours, ref, atol=2e-5, rtol=2e-5)


def test_from_hf_guards(hf_model, tmp_path):
    from lazzaro_tpu.models.encoder import HFTokenizerAdapter
    from lazzaro_tpu.models.wordpiece import WordPieceTokenizer

    # tokenizer XOR vocab_file
    vf = tmp_path / "v.txt"
    vf.write_text("[PAD]\n[UNK]\n[CLS]\n[SEP]\na\n", encoding="utf-8")
    tok = WordPieceTokenizer.from_vocab_file(str(vf))
    with pytest.raises(ValueError, match="not both"):
        TextEncoder.from_hf(hf_model, tokenizer=tok, vocab_file=str(vf))

    # vocab larger than the checkpoint's embedding table → reject (silent
    # NaN from Flax Embed OOB otherwise)
    big = tmp_path / "big.txt"
    big.write_text("\n".join(["[PAD]", "[UNK]", "[CLS]", "[SEP]"]
                             + [f"t{i}" for i in range(200)]) + "\n",
                   encoding="utf-8")
    with pytest.raises(ValueError, match="vocab_size"):
        TextEncoder.from_hf(hf_model, vocab_file=str(big))

    # HFTokenizerAdapter surfaces a nonzero pad id to the guard
    hf_tok = transformers.BertTokenizer(str(vf), do_lower_case=True)
    hf_tok.pad_token = "[UNK]"           # forces pad_token_id=1
    adapter = HFTokenizerAdapter(hf_tok, max_len=16)
    assert adapter.pad_id == 1
    with pytest.raises(ValueError, match="pad id"):
        TextEncoder.from_hf(hf_model, tokenizer=adapter)


def test_from_hf_encode_pipeline(hf_model):
    """End-to-end encode() through the hash tokenizer: shape + normalization
    + determinism (vocab is wrong for real retrieval, pipeline must work)."""
    enc = TextEncoder.from_hf(hf_model, max_len=16)
    out = enc.encode_batch(["hello world", "another sentence"])
    assert out.shape == (2, 32)
    np.testing.assert_allclose(np.linalg.norm(out, axis=1), 1.0, atol=1e-5)
    out2 = enc.encode_batch(["hello world", "another sentence"])
    np.testing.assert_allclose(out, out2)
