"""Sequence-parallel LM training: ring attention inside the decoder.

Long-context is first-class in the MODEL, not just a standalone kernel
(SURVEY §2.3 "sequence parallelism"): the train step shards activations
along time over an 'sp' mesh axis and routes every layer's attention
through the ppermute ring, composed with data parallelism. The oracle is
the ordinary single-device train step — same params, same batch, same
loss and updated params to float tolerance.
"""

# Compile-heavy (multi-second XLA compiles / 100k-row arenas): the
# default lane must stay inside a driver window; run the full lane
# with no -m filter for round gates.
pytestmark = __import__("pytest").mark.slow

import dataclasses

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from lazzaro_tpu.models.llm import (Decoder, LMConfig, make_seq_parallel_train_step,
                                    make_train_step)
from lazzaro_tpu.parallel.mesh import make_mesh

CFG = dataclasses.replace(LMConfig.tiny(), max_seq=64)


def _setup(T=32, B=4):
    tokens = jnp.asarray(np.random.RandomState(0).randint(0, 250, (B, T)),
                         jnp.int32)
    mask = jnp.ones_like(tokens)
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T))
    params = Decoder(CFG).init(jax.random.PRNGKey(0), tokens, positions)["params"]
    return tokens, mask, params


@pytest.mark.parametrize("axes,sizes", [(("sp",), (8,)),
                                        (("data", "sp"), (2, 4))])
def test_seq_parallel_matches_single_device(axes, sizes):
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh from conftest")
    tokens, mask, params = _setup()
    opt = optax.sgd(1e-2)

    mesh = make_mesh(axes, sizes)
    step_sp = make_seq_parallel_train_step(CFG, opt, mesh)
    p0 = jax.tree_util.tree_map(jnp.copy, params)
    p_sp, _, loss_sp = step_sp(p0, opt.init(p0), tokens, mask)

    step_ref = make_train_step(CFG, opt)
    p1 = jax.tree_util.tree_map(jnp.copy, params)
    p_ref, _, loss_ref = step_ref(p1, opt.init(p1), tokens, mask)

    assert float(loss_sp) == pytest.approx(float(loss_ref), abs=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                atol=1e-4, rtol=1e-4),
        p_sp, p_ref)


def test_seq_parallel_loss_decreases_over_steps():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh from conftest")
    tokens, mask, params = _setup()
    opt = optax.adam(1e-3)
    mesh = make_mesh(("data", "sp"), (2, 4))
    step = make_seq_parallel_train_step(CFG, opt, mesh)
    opt_state = opt.init(params)
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, tokens, mask)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_seq_parallel_rejects_gemma2_features():
    mesh = make_mesh(("sp",), (len(jax.devices()),))
    bad = dataclasses.replace(CFG, sliding_window=8)
    with pytest.raises(ValueError, match="sliding"):
        make_seq_parallel_train_step(bad, optax.sgd(1e-2), mesh)


def test_ring_branch_rejects_gemma2_in_attention():
    """The guard lives IN Attention, so a direct Decoder(cfg, seq_mesh=...)
    with Gemma-2 numerics errors instead of silently dropping softcap/
    sliding-window."""
    import jax.numpy as jnp

    mesh = make_mesh(("sp",), (len(jax.devices()),))
    bad = dataclasses.replace(LMConfig.tiny(), attn_softcap=50.0)
    model = Decoder(bad, seq_mesh=mesh)
    T = 8 * len(jax.devices())
    tokens = jnp.zeros((1, T), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(T)[None], (1, T))
    with pytest.raises(ValueError, match="ring attention supports"):
        model.init(jax.random.PRNGKey(0), tokens, pos)
