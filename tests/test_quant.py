"""Int8 serving shadow (ops/quant.py, VERDICT r3 next #7 "int8 arena").

Retrieval is HBM-bound; the quantized shadow halves scan bytes. These tests
pin the quantization error envelope, ranking parity with the exact scan,
lazy shadow refresh on arena mutation, and that consolidation's dedup gate
keeps using the exact master (its 0.95 threshold sits inside the int8 error
band).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from lazzaro_tpu.config import MemoryConfig
from lazzaro_tpu.core.index import MemoryIndex
from lazzaro_tpu.core.memory_system import MemorySystem
from lazzaro_tpu.ops.quant import quantize_rows, quantized_topk


def _rows(n, d, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d)).astype(np.float32)
    return x / np.linalg.norm(x, axis=1, keepdims=True)


def test_quantize_roundtrip_error():
    x = _rows(256, 64)
    q, s = quantize_rows(jnp.asarray(x))
    back = np.asarray(q, np.float32) * np.asarray(s)[:, None]
    err = np.abs(back - x).max()
    assert err <= 1.0 / 127 + 1e-6        # symmetric per-row int8 bound
    # zero rows: scale 0, no NaNs
    q0, s0 = quantize_rows(jnp.zeros((4, 64)))
    assert float(np.abs(np.asarray(q0)).max()) == 0.0
    assert float(np.asarray(s0).max()) == 0.0


def test_quantized_topk_matches_exact_ranking():
    n, d, nq = 3000, 64, 600               # nq > 512 exercises chunked_map
    emb = _rows(n, d)
    queries = _rows(nq, d, seed=1)
    mask = np.ones(n, bool)
    mask[7] = False
    q8, s = quantize_rows(jnp.asarray(emb))
    scores, rows = quantized_topk(q8, s, jnp.asarray(mask),
                                  jnp.asarray(queries), 5)
    rows = np.asarray(rows)
    exact = (queries @ emb.T)
    exact[:, 7] = -np.inf
    exact_top1 = exact.argmax(axis=1)
    # top-1 agreement on random (well-separated) data; scores within the
    # quantization envelope
    agree = (rows[:, 0] == exact_top1).mean()
    assert agree >= 0.97, f"top-1 agreement {agree}"
    # every disagreement must be a quantization-scale near-tie, not a miss
    mism = np.nonzero(rows[:, 0] != exact_top1)[0]
    gap = exact[mism, exact_top1[mism]] - exact[mism, rows[mism, 0]]
    assert gap.max(initial=0.0) < 2.5e-2, f"non-tie ranking miss: {gap.max()}"
    np.testing.assert_allclose(
        np.asarray(scores)[:, 0],
        exact[np.arange(nq), rows[:, 0]], atol=2e-2)
    assert not (rows == 7).any(), "masked row leaked into results"


def test_index_shadow_refreshes_on_mutation():
    d = 16
    idx = MemoryIndex(dim=d, capacity=64, int8_serving=True)
    e = np.eye(d, dtype=np.float32)
    idx.add(["a", "b"], e[:2], [0.5] * 2, [0.0] * 2, ["semantic"] * 2,
            ["default"] * 2, "u1")
    (ids, _), = idx.search_batch(e[0][None, :], "u1", k=1)
    assert ids == ["a"]
    # mutate: new node closer to the query direction than "a"? add exact dup
    idx.add(["c"], e[1][None, :], [0.9], [0.0], ["semantic"], ["default"], "u1")
    (ids2, _), = idx.search_batch(e[1][None, :], "u1", k=2)
    assert set(ids2) >= {"b"}, ids2       # shadow saw the post-mutation arena
    assert not idx._int8_dirty
    # metadata sweeps must NOT invalidate the shadow (no ~full-arena
    # requant per access-count bump)
    idx.update_access(["a"])
    assert not idx._int8_dirty


def test_system_behavior_parity_with_int8_serving(tmp_path):
    # Same conversations under exact and int8-serving configs: identical
    # graph evolution (the dedup gate is pinned to the exact master) and
    # identical retrieval results.
    def drive(flag, sub):
        cfg = MemoryConfig(journal=False, int8_serving=flag)
        ms = MemorySystem(enable_async=False, db_dir=str(tmp_path / sub),
                          verbose=False, load_from_disk=False, config=cfg)
        for _ in range(2):
            ms.start_conversation()
            ms.chat("I work as a data engineer on a big ETL project.")
            ms.end_conversation()
        nodes = ms.buffer.size()
        hits = [n.content for n in ms.search_memories("data engineer job")]
        ms.close()
        return nodes, hits

    exact_nodes, exact_hits = drive(False, "db_exact")
    int8_nodes, int8_hits = drive(True, "db_int8")
    assert int8_nodes == exact_nodes
    assert int8_hits == exact_hits
    assert any("data engineer" in h for h in int8_hits)


def test_fused_ingest_maintains_shadow_incrementally():
    """ISSUE 3 tentpole invariant: once the shadow exists, the fused ingest
    scatter keeps the int8 codes fresh IN-KERNEL (O(batch) scatter) — no
    host-side O(arena) re-quantize on write, no dirty round trip — and the
    maintained codes are bit-identical to a from-scratch requantize."""
    from lazzaro_tpu.ops.quant import quantize_rows

    d, n0, n1 = 16, 40, 24
    rng = np.random.default_rng(3)
    idx = MemoryIndex(dim=d, capacity=255, int8_serving=True)
    idx.ingest_batch([f"a{i}" for i in range(n0)],
                     rng.standard_normal((n0, d)).astype(np.float32),
                     [0.5] * n0, [0.0] * n0, ["semantic"] * n0,
                     ["default"] * n0, "u")
    assert idx._int8_dirty                     # no shadow existed to maintain
    idx.search_batch(rng.standard_normal((1, d)).astype(np.float32), "u", k=3)
    assert not idx._int8_dirty                 # lazy build happened
    idx.ingest_batch([f"b{i}" for i in range(n1)],
                     rng.standard_normal((n1, d)).astype(np.float32),
                     [0.5] * n1, [0.0] * n1, ["semantic"] * n1,
                     ["default"] * n1, "u")
    assert not idx._int8_dirty                 # maintained in the kernel
    q8, sc = idx._int8_shadow
    q8_full, sc_full = quantize_rows(idx.state.emb)
    np.testing.assert_array_equal(np.asarray(q8), np.asarray(q8_full))
    np.testing.assert_allclose(np.asarray(sc), np.asarray(sc_full))
    # the dedup-fused path maintains it too (duplicates scatter nowhere)
    pending = idx.ingest_batch_dedup(
        rng.standard_normal((8, d)).astype(np.float32), [0.5] * 8,
        [0.0] * 8, ["semantic"] * 8, ["default"] * 8, "u", dedup_gate=0.95)
    idx.commit_ingest_dedup(pending, [f"c{i}" for i in range(8)])
    assert not idx._int8_dirty
    q8, sc = idx._int8_shadow
    q8_full, sc_full = quantize_rows(idx.state.emb)
    np.testing.assert_array_equal(np.asarray(q8), np.asarray(q8_full))
    np.testing.assert_allclose(np.asarray(sc), np.asarray(sc_full))


def test_int8_serving_survives_snapshot_restore(tmp_path):
    cfg = MemoryConfig(journal=False, int8_serving=True)
    ms = MemorySystem(enable_async=False, db_dir=str(tmp_path / "db"),
                      verbose=False, load_from_disk=False, config=cfg)
    ms.start_conversation()
    ms.chat("I work as a data engineer on a big ETL project.")
    ms.end_conversation()
    snap = str(tmp_path / "snap")
    ms.save_snapshot(snap)
    ms.load_snapshot(snap)                 # index object is replaced
    assert ms.index.int8_serving
    hits = [n.content for n in ms.search_memories("data engineer")]
    assert any("data engineer" in h for h in hits)
    assert ms.index._int8_shadow is not None   # int8 path actually served
    ms.close()
