"""Elastic recovery: the turn journal survives a process crash.

The reference has no failure-detection/recovery story (SURVEY §5): it
persists only at conversation end (memory_system.py:648), so a crash
mid-conversation silently loses every buffered turn. Here each
``add_to_short_term`` appends to a CRC-framed WAL; a new instance on the same
db_dir replays it and re-opens the conversation.
"""

from lazzaro_tpu import MemorySystem
from tests.fakes import MockEmbedder, MockLLM, extraction_response


def _make(tmp_db, llm=None, **kw):
    return MemorySystem(
        llm_provider=llm or MockLLM(), embedding_provider=MockEmbedder(dim=32),
        db_dir=tmp_db, enable_async=False, verbose=False, **kw)


def test_crashed_turns_recovered(tmp_db):
    ms = _make(tmp_db)
    ms.start_conversation()
    ms.add_to_short_term("User is a marine biologist", "semantic", 0.9)
    ms.add_to_short_term("User visited a coral reef today", "episodic", 0.7)
    # Simulated crash: no end_conversation, no close.

    llm = MockLLM(sniffers={"Extract distinct": extraction_response([
        {"content": "User is a marine biologist", "type": "semantic",
         "salience": 0.9, "topic": "work"}])})
    ms2 = _make(tmp_db, llm=llm)
    assert ms2.conversation_active
    contents = [t["content"] for t in ms2.short_term_memory]
    assert contents == ["User is a marine biologist",
                        "User visited a coral reef today"]

    # The recovered conversation consolidates normally.
    ms2.end_conversation()
    assert any("marine" in n.content
               for n in ms2.search_memories("User is a marine biologist"))


def test_journal_cleared_after_consolidation(tmp_db):
    llm = MockLLM(sniffers={"Extract distinct": extraction_response([
        {"content": "User likes tea", "type": "semantic",
         "salience": 0.6, "topic": "personal"}])})
    ms = _make(tmp_db, llm=llm)
    ms.start_conversation()
    ms.add_to_short_term("User likes tea", "semantic", 0.6)
    ms.end_conversation()

    ms2 = _make(tmp_db)
    assert not ms2.conversation_active
    assert ms2.short_term_memory == []


def test_journal_is_per_user(tmp_db):
    ms = _make(tmp_db, user_id="alice")
    ms.start_conversation()
    ms.add_to_short_term("Alice plays violin", "semantic", 0.8)

    bob = _make(tmp_db, user_id="bob")
    assert not bob.conversation_active
    alice2 = _make(tmp_db, user_id="alice")
    assert alice2.conversation_active
    assert alice2.short_term_memory[0]["content"] == "Alice plays violin"


def test_journal_disabled_flag(tmp_db):
    ms = _make(tmp_db)
    ms.config.journal = False
    ms._setup_journal()
    assert ms._journal is None
    ms.start_conversation()
    ms.add_to_short_term("ephemeral turn", "semantic", 0.5)

    ms2 = _make(tmp_db)
    # The flag-off turn was never journaled, so nothing to recover.
    assert all(t["content"] != "ephemeral turn" for t in ms2.short_term_memory)


def test_start_conversation_consolidates_recovered_turns(tmp_db):
    """A recovered buffer must survive the common post-restart '/start' flow
    (not be silently discarded the way a normal abandoned buffer is)."""
    ms = _make(tmp_db)
    ms.start_conversation()
    ms.add_to_short_term("User speaks Basque", "semantic", 0.9)
    # crash

    llm = MockLLM(sniffers={"Extract distinct": extraction_response([
        {"content": "User speaks Basque", "type": "semantic",
         "salience": 0.9, "topic": "personal"}])})
    ms2 = _make(tmp_db, llm=llm)
    assert ms2._recovered_turns
    ms2.start_conversation()           # consolidates, then opens fresh buffer
    assert ms2.short_term_memory == []
    assert any("Basque" in n.content
               for n in ms2.search_memories("User speaks Basque"))


def test_abandoned_buffer_discarded_on_start(tmp_db):
    """Reference parity: a NON-recovered active buffer is dropped by
    start_conversation, and its journal entries go with it."""
    ms = _make(tmp_db)
    ms.start_conversation()
    ms.add_to_short_term("abandoned turn", "semantic", 0.5)
    ms.start_conversation()
    assert ms.short_term_memory == []

    ms2 = _make(tmp_db)
    assert all(t["content"] != "abandoned turn" for t in ms2.short_term_memory)


def test_async_consolidation_does_not_wipe_new_turns(tmp_db):
    """Background consolidation finishing after a new conversation started
    must leave the new conversation's turns in the WAL."""
    llm = MockLLM(sniffers={"Extract distinct": extraction_response([
        {"content": "User ran a marathon", "type": "episodic",
         "salience": 0.8, "topic": "health"}])})
    ms = MemorySystem(llm_provider=llm, embedding_provider=MockEmbedder(dim=32),
                      db_dir=tmp_db, enable_async=True, verbose=False)
    ms.start_conversation()
    ms.add_to_short_term("User ran a marathon", "episodic", 0.8)
    ms.end_conversation()              # queues background consolidation
    ms.start_conversation()
    ms.add_to_short_term("fresh turn after restart of convo", "semantic", 0.6)
    ms._drain_background()             # consolidation completes + journal sync
    ms.close()

    ms2 = _make(tmp_db)
    contents = [t["content"] for t in ms2.short_term_memory]
    assert contents == ["fresh turn after restart of convo"]


def test_load_from_disk_false_skips_replay(tmp_db):
    ms = _make(tmp_db)
    ms.start_conversation()
    ms.add_to_short_term("persisted-in-wal", "semantic", 0.5)
    # crash

    clean = _make(tmp_db, load_from_disk=False)
    assert not clean.conversation_active
    assert clean.short_term_memory == []
    # ...and the crashed turns are still recoverable by a loading instance.
    ms2 = _make(tmp_db)
    assert [t["content"] for t in ms2.short_term_memory] == ["persisted-in-wal"]


def test_injected_store_skips_journal():
    """In-memory stores (no db_dir attribute) get no journal."""

    class NullStore:
        def close(self):
            pass

    ms = MemorySystem(llm_provider=MockLLM(),
                      embedding_provider=MockEmbedder(dim=32),
                      store=NullStore(), load_from_disk=False,
                      enable_async=False, verbose=False)
    assert ms._journal is None
